"""Synthetic "quote-the-context" checkpoints for benchmarking.

No public checkpoint ships in this image (zero egress), and a RANDOM-init
model's output has two properties that break realistic end-to-end
measurement: its greedy continuation repeats essentially no n-grams
(speculative prompt-lookup can never land — measured 251/256 unique
tokens, 0 acceptances), and its sampled byte stream almost never forms
valid UTF-8, so the incremental detokenizer buffers nearly the whole
generation and "streaming" TTFT at a UI degrades to completion time.

:func:`quote_params` builds a full-size random tree whose OUTPUT
statistics match a real co-pilot's instead: embeddings are
near-orthogonal and the lm_head maps each token's embedding to a fixed
successor, with the successor cycles laid INSIDE the byte tokenizer's
printable-ASCII id range. Every forward still pays the full model
compute (all transformer layers keep their random weights; the logit
margin ~4*hidden is so large that sampling at any sane temperature
follows the cycle), so decode/prefill cost is identical to a real
checkpoint of the same config — but greedy/sampled output settles into a
repeating printable phrase: prompt-lookup drafts land (the speculation
benchmark) and the detokenizer streams byte-per-token (the UI-boundary
TTFT benchmark). bench.py (BENCH_WORKLOAD=quote) and tools/e2e_bench.py
share this construction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .configs import ModelConfig

# The byte tokenizer maps byte b to id b (specials live above 256); the
# printable range streams through UTF-8 incremental decoding one byte at
# a time.
_ASCII_LO, _ASCII_HI = 32, 127
_CYCLE = 16


def successor_map(vocab: int, mode: str = "quote") -> np.ndarray:
    """succ[t] for every token id: printable-ASCII ids cycle within the
    printable range; every other id funnels into the printable range so
    one step after any stray token the stream is printable forever.

    ``mode`` selects the cycle statistics of the greedy output:

    - ``"quote"`` (default): blocks of ``_CYCLE`` consecutive ids — the
      output repeats a 16-token phrase, so trailing n-grams recur fast
      and prompt-lookup drafts land (the quote-the-context statistic).
    - ``"freeform"``: ONE pseudo-random cycle over the whole printable
      range (a seeded permutation, not the +1 ordering — consecutive-
      byte bigrams occur in natural prompt text and would hand the
      n-gram index spurious hits). Trailing bigrams recur only after a
      full 95-token lap, so prompt-lookup drafting scores ~0 on any
      normal-length completion — the free-form statistic where only a
      DRAFT MODEL sharing the map (serve/draft_model.py) can win.
    """
    ids = np.arange(_ASCII_LO, _ASCII_HI)
    succ = np.empty(vocab, np.int64)
    # stray ids -> deterministic printable entry points
    succ[:] = _ASCII_LO + (np.arange(vocab) % len(ids))
    if mode == "freeform":
        order = np.random.default_rng(11).permutation(ids)
        succ[order] = np.roll(order, -1)     # one 95-token cycle
        return succ
    if mode != "quote":
        raise ValueError(f"successor_map mode must be quote|freeform, "
                         f"got {mode!r}")
    for start in range(0, len(ids), _CYCLE):
        block = ids[start: start + _CYCLE]
        succ[block] = np.roll(block, -1)
    return succ


def quote_params(config: ModelConfig, key: jax.Array,
                 dtype=jnp.bfloat16, quantized: bool = False,
                 mode: str = "quote", quant: str = "int8") -> dict:
    """Full-size tree (random transformer layers of the config's FAMILY —
    llama or mixtral — full compute) with the quote-workload
    embed/lm_head. ``quantized=True`` returns quantized matmul leaves at
    ``quant`` (``int8`` per-channel or ``int4`` group-wise; both
    families stream straight to the fused quantized tree). Requires an
    untied lm_head.

    ``mode="freeform"`` swaps the 16-token repeat cycles for one
    pseudo-random 95-token cycle (see :func:`successor_map`): greedy
    output stops repeating n-grams, so prompt-lookup drafting measures
    ~0 acceptances — the free-form workload of the draft-model spec
    bench. The successor map depends only on (vocab, mode), so a TARGET
    and a smaller DRAFTER config built with the same (vocab, mode)
    follow the same cycle and the drafter's greedy proposals match the
    target's continuation — the synthetic stand-in for "a small model
    predicts the big model's easy tokens" that lets CPU tests and the
    no-checkpoint bench measure draft-model speculation end to end."""
    from . import family_for
    from .quant import quantize_params

    if config.tie_embeddings:
        raise ValueError("quote workload needs an untied lm_head")
    family = family_for(config)
    if quantized and hasattr(family, "init_params_quantized"):
        # Both families stream straight to the fused quantized tree
        # (llama and mixtral expose init_params_quantized).
        params = family.init_params_quantized(config, key, dtype=dtype,
                                              quant=quant)
    else:
        params = dict(family.init_params(config, key, dtype=dtype))
        if quantized:
            params = quantize_params(params, mode=quant)

    # Damp the residual-writing projections (wo, w_down / expert
    # w_down): the cycle construction needs the residual stream to stay
    # dominated by the input embedding, and at small hidden sizes the
    # random layers' perturbation otherwise out-shouts the successor
    # margin (observed at the `tiny` config). Compute cost is unchanged
    # — the matmuls still run at full shape.
    from .quant import QTensor, QTensor4

    def damp(leaf):
        if isinstance(leaf, (QTensor, QTensor4)):
            # Scales are linear in the weight for both precisions.
            return type(leaf)(q=leaf.q, s=leaf.s * 0.1)
        return leaf * 0.1

    layers = dict(params["layers"])
    for name in ("wo", "w_down"):
        if name in layers:
            layers[name] = damp(layers[name])
    params = dict(params)
    params["layers"] = layers

    V, H = config.vocab_size, config.hidden_size
    emb = np.asarray(jax.random.normal(jax.random.PRNGKey(7), (V, H),
                                       jnp.float32))
    succ = successor_map(V, mode=mode)
    # lm_head[:, j] = 4 * sum_{succ(t)=j} w_t * emb[t]: logits_j(t)
    # contains 4*w_t*|emb[t]|^2 ~ 4H exactly when j = succ(t). Printable
    # tokens get w=1 (a pure in-range permutation); the ~V/95 stray
    # tokens funnelled into each printable column are down-weighted by
    # 1/sqrt(strays-per-column) so their summed cross-term noise stays
    # at the O(4*sqrt(H)) of the permutation — an unweighted funnel at
    # bench-1b scale (344 strays/column) put ~3300-sigma cross terms
    # against the 4H ~ 8192 signal and broke the cycle on a nontrivial
    # fraction of steps.
    weights = np.full(V, 1.0, np.float32)
    stray = np.ones(V, bool)
    stray[_ASCII_LO:_ASCII_HI] = False
    per_col = max(1, int(stray.sum()) // (_ASCII_HI - _ASCII_LO))
    weights[stray] = 1.0 / np.sqrt(per_col)
    lm_t = np.zeros((V, H), np.float32)
    np.add.at(lm_t, succ, emb * weights[:, None])
    lm = lm_t.T * 4.0
    params = dict(params)
    # Drop the init head before uploading the quote head: at 8B dims the
    # pair is ~1.6 GB of HBM that must not coexist with the new leaves.
    params.pop("embed", None)
    old_head = params.pop("lm_head", None)
    del old_head
    params["embed"] = jnp.asarray(emb, dtype)
    if quantized:
        # Quantize HOST-side (exact mirrors of quant.quantize /
        # quant.quantize4, axis=-2): uploading lm as f32 to quantize on
        # device is a 2.1 GB HBM spike at 8B dims that OOM'd the
        # spec-enabled quote bench.
        K = lm.shape[0]
        if (quant == "int4" and K % 2 == 0
                and (K % 128 == 0 or K % 64 == 0)):
            group = 128 if K % 128 == 0 else 64
            g = lm.reshape(K // group, group, V)
            amax = np.abs(g).max(axis=1, keepdims=True)
            s = np.where(amax > 0, amax / 7.0, 1.0).astype(np.float32)
            qv = np.clip(np.round(g / s), -7, 7).astype(np.int32)
            qv = qv.reshape(K, V)
            packed = ((qv[:K // 2] + 8) | ((qv[K // 2:] + 8) << 4))
            packed = packed.astype(np.uint8).view(np.int8)
            params["lm_head"] = QTensor4(q=jnp.asarray(packed),
                                         s=jnp.asarray(np.squeeze(s, 1)))
        else:
            amax = np.abs(lm).max(axis=0, keepdims=True)
            s = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
            q = np.clip(np.round(lm / s), -127, 127).astype(np.int8)
            params["lm_head"] = QTensor(q=jnp.asarray(q), s=jnp.asarray(s))
    else:
        params["lm_head"] = jnp.asarray(lm, dtype)
    return params
