"""Token sampling: greedy, temperature, top-k, top-p — jit-friendly.

All functions take f32 logits [B, vocab] and return token ids [B]. The
option set mirrors what the Ollama contract exposes via ``options``
(serve/backend.py GenerateOptions), so server-side sampling is a drop-in
for what the reference delegated to Ollama.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .layers import NEG_INF


def greedy(logits: jax.Array) -> jax.Array:
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def _apply_top_k(logits: jax.Array, top_k: int) -> jax.Array:
    if top_k <= 0:
        return logits
    k = min(top_k, logits.shape[-1])
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, NEG_INF, logits)


def _apply_top_p(logits: jax.Array, top_p: float) -> jax.Array:
    if top_p >= 1.0:
        return logits
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # Keep the smallest prefix with cumulative prob >= top_p (always >= 1 tok).
    keep = cum - probs < top_p
    threshold = jnp.min(jnp.where(keep, sorted_logits, jnp.inf), axis=-1,
                        keepdims=True)
    return jnp.where(logits < threshold, NEG_INF, logits)


def sample(logits: jax.Array, key: jax.Array, temperature: float = 0.0,
           top_k: int = 0, top_p: float = 1.0) -> jax.Array:
    """Sample next tokens. temperature<=0 means greedy (matching Ollama's
    deterministic mode)."""
    if temperature <= 0.0:
        return greedy(logits)
    logits = logits / jnp.asarray(temperature, logits.dtype)
    logits = _apply_top_k(logits, top_k)
    logits = _apply_top_p(logits, top_p)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)
