"""Token sampling: greedy, temperature, top-k, top-p.

Three implementations of the same semantics:

- :func:`sample` — jit-friendly JAX, f32 logits [B, vocab] -> ids [B], one
  shared option set for the whole batch. Used by the reference generation
  loops (models/generate.py).
- :func:`sample_batched` — jit-friendly JAX with **per-row** options and
  per-row PRNG keys. Used inside the continuous-batching scheduler's fused
  decode step (serve/scheduler.py), where every batch row belongs to a
  different request: sampling on-device shrinks the per-tick device->host
  transfer from the full [B, vocab] logits to B int32 tokens — the
  difference between ~92 ms and ~3.5 ms per tick on a tunneled TPU host.
- :func:`sample_np` — host-side numpy over a single row; the hermetic
  reference oracle for the device samplers' filtering semantics.

The option set mirrors what the Ollama contract exposes via ``options``
(serve/backend.py GenerateOptions), so server-side sampling is a drop-in
for what the reference delegated to Ollama.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .layers import NEG_INF


def greedy(logits: jax.Array) -> jax.Array:
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def _apply_top_k(logits: jax.Array, top_k: int) -> jax.Array:
    if top_k <= 0:
        return logits
    k = min(top_k, logits.shape[-1])
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, NEG_INF, logits)


def _apply_top_p(logits: jax.Array, top_p: float) -> jax.Array:
    if top_p >= 1.0:
        return logits
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # Keep the smallest prefix with cumulative prob >= top_p (always >= 1
    # tok — the explicit set makes that hold even for top_p <= 0).
    keep = cum - probs < top_p
    keep = keep.at[..., 0].set(True)
    threshold = jnp.min(jnp.where(keep, sorted_logits, jnp.inf), axis=-1,
                        keepdims=True)
    return jnp.where(logits < threshold, NEG_INF, logits)


def sample(logits: jax.Array, key: jax.Array, temperature: float = 0.0,
           top_k: int = 0, top_p: float = 1.0) -> jax.Array:
    """Sample next tokens. temperature<=0 means greedy (matching Ollama's
    deterministic mode)."""
    if temperature <= 0.0:
        return greedy(logits)
    logits = logits / jnp.asarray(temperature, logits.dtype)
    logits = _apply_top_k(logits, top_k)
    logits = _apply_top_p(logits, top_p)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def sample_batched(logits: jax.Array, keys: jax.Array, temperature: jax.Array,
                   top_k: jax.Array, top_p: jax.Array,
                   top_c: int = 64) -> tuple[jax.Array, jax.Array]:
    """Per-row sampling: logits [B,V] f32, keys [B,2] (one PRNG key per
    row), temperature/top_k/top_p [B]. Returns (tokens [B] int32,
    advanced keys [B,2]).

    Same filters as :func:`sample` / :func:`sample_np`, vectorised over
    per-row option values: temperature<=0 is greedy; top_k<=0 disables
    top-k; top_p>=1 disables top-p; top_p<=0 degrades to top-1.

    Runs inside the fused decode step, so it must be cheap on the hot
    path: candidates are truncated to the ``top_c`` highest logits via
    ``lax.top_k`` instead of a full-vocab sort (a 32×128k argsort costs
    more than the whole decode step on TPU). Exact when the vocab fits in
    ``top_c`` or the caller's top_k is <= top_c; otherwise the (numerically
    negligible) tail mass past the top-64 candidates is dropped — the
    standard TPU-serving truncation. Two minor divergences from sample_np:
    per-row dynamic k keeps exactly k tokens (ties at the k-th value break
    by sort order), and sampling never leaves the top-``top_c`` set.
    """
    B, V = logits.shape
    C = min(top_c, V)
    sorted_logits, order = jax.lax.top_k(logits, C)        # [B,C] descending
    ranks = jnp.arange(C)[None, :]
    keep_k = (top_k[:, None] <= 0) | (ranks < top_k[:, None])
    temp = jnp.maximum(temperature, 1e-6)[:, None]
    # top-p is evaluated on the top-k-filtered, renormalised distribution —
    # the same order sample/sample_np apply the filters in.
    k_masked = jnp.where(keep_k, sorted_logits / temp, NEG_INF)
    probs = jax.nn.softmax(k_masked, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_p = (top_p[:, None] >= 1.0) | ((cum - probs) < top_p[:, None])
    keep = keep_k & keep_p
    keep = keep.at[:, 0].set(True)                         # never empty
    masked = jnp.where(keep, sorted_logits / temp, NEG_INF)

    split = jax.vmap(lambda k: jax.random.split(k, 2))(keys)   # [B,2,2]
    new_keys, subs = split[:, 0], split[:, 1]
    choice = jax.vmap(jax.random.categorical)(subs, masked)    # [B] ranks
    sampled = jnp.take_along_axis(order, choice[:, None], axis=-1)[:, 0]
    tok = jnp.where(temperature <= 0.0,
                    jnp.argmax(logits, axis=-1), sampled).astype(jnp.int32)
    return tok, new_keys


def sample_np(logits: np.ndarray, rng: np.random.Generator,
              temperature: float = 0.0, top_k: int = 0,
              top_p: float = 1.0) -> int:
    """Numpy twin of :func:`sample` for one row of logits [vocab].

    Same filtering semantics: temperature<=0 is greedy; top-k keeps the k
    highest logits (ties at the k-th value survive, like lax.top_k's
    threshold compare); top-p keeps the smallest probability-sorted prefix
    whose cumulative mass reaches top_p (always at least one token).
    """
    # float64 throughout: Generator.choice checks sum(p)==1 to float64
    # tolerance, which float32 softmax fails at real vocab sizes (~128k).
    logits = np.asarray(logits, np.float64)
    if temperature <= 0.0:
        return int(np.argmax(logits))
    logits = logits / max(temperature, 1e-6)
    if top_k > 0:
        k = min(top_k, logits.shape[-1])
        kth = np.sort(logits)[-k]
        logits = np.where(logits < kth, NEG_INF, logits)
    if top_p < 1.0:
        order = np.argsort(logits)[::-1]
        sorted_logits = logits[order]
        probs = _softmax_np(sorted_logits)
        cum = np.cumsum(probs)
        keep = (cum - probs) < top_p
        # top_p <= 0 keeps nothing under the strict compare; degrade to
        # top-1 like the JAX twin (threshold=inf keeps only the max).
        threshold = (sorted_logits[keep].min() if keep.any()
                     else sorted_logits[0])
        logits = np.where(logits < threshold, NEG_INF, logits)
    probs = _softmax_np(logits)
    return int(rng.choice(logits.shape[-1], p=probs))


def _softmax_np(x: np.ndarray) -> np.ndarray:
    e = np.exp(x - x.max())
    p = e / e.sum()
    # Renormalise exactly — np.random choice requires sum(p) == 1.
    return p / p.sum()
