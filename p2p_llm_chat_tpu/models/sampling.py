"""Token sampling: greedy, temperature, top-k, top-p.

Two implementations of the same semantics:

- :func:`sample` — jit-friendly JAX, f32 logits [B, vocab] -> ids [B], one
  shared option set for the whole batch. Used by the reference generation
  loops (models/generate.py).
- :func:`sample_np` — host-side numpy over a single row, per-request
  options and per-request RNG. Used by the continuous-batching scheduler
  (serve/scheduler.py), where every batch row belongs to a different
  request with its own temperature/top-k/top-p/seed.

The option set mirrors what the Ollama contract exposes via ``options``
(serve/backend.py GenerateOptions), so server-side sampling is a drop-in
for what the reference delegated to Ollama.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .layers import NEG_INF


def greedy(logits: jax.Array) -> jax.Array:
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def _apply_top_k(logits: jax.Array, top_k: int) -> jax.Array:
    if top_k <= 0:
        return logits
    k = min(top_k, logits.shape[-1])
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, NEG_INF, logits)


def _apply_top_p(logits: jax.Array, top_p: float) -> jax.Array:
    if top_p >= 1.0:
        return logits
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # Keep the smallest prefix with cumulative prob >= top_p (always >= 1
    # tok — the explicit set makes that hold even for top_p <= 0).
    keep = cum - probs < top_p
    keep = keep.at[..., 0].set(True)
    threshold = jnp.min(jnp.where(keep, sorted_logits, jnp.inf), axis=-1,
                        keepdims=True)
    return jnp.where(logits < threshold, NEG_INF, logits)


def sample(logits: jax.Array, key: jax.Array, temperature: float = 0.0,
           top_k: int = 0, top_p: float = 1.0) -> jax.Array:
    """Sample next tokens. temperature<=0 means greedy (matching Ollama's
    deterministic mode)."""
    if temperature <= 0.0:
        return greedy(logits)
    logits = logits / jnp.asarray(temperature, logits.dtype)
    logits = _apply_top_k(logits, top_k)
    logits = _apply_top_p(logits, top_p)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def sample_np(logits: np.ndarray, rng: np.random.Generator,
              temperature: float = 0.0, top_k: int = 0,
              top_p: float = 1.0) -> int:
    """Numpy twin of :func:`sample` for one row of logits [vocab].

    Same filtering semantics: temperature<=0 is greedy; top-k keeps the k
    highest logits (ties at the k-th value survive, like lax.top_k's
    threshold compare); top-p keeps the smallest probability-sorted prefix
    whose cumulative mass reaches top_p (always at least one token).
    """
    # float64 throughout: Generator.choice checks sum(p)==1 to float64
    # tolerance, which float32 softmax fails at real vocab sizes (~128k).
    logits = np.asarray(logits, np.float64)
    if temperature <= 0.0:
        return int(np.argmax(logits))
    logits = logits / max(temperature, 1e-6)
    if top_k > 0:
        k = min(top_k, logits.shape[-1])
        kth = np.sort(logits)[-k]
        logits = np.where(logits < kth, NEG_INF, logits)
    if top_p < 1.0:
        order = np.argsort(logits)[::-1]
        sorted_logits = logits[order]
        probs = _softmax_np(sorted_logits)
        cum = np.cumsum(probs)
        keep = (cum - probs) < top_p
        # top_p <= 0 keeps nothing under the strict compare; degrade to
        # top-1 like the JAX twin (threshold=inf keeps only the max).
        threshold = (sorted_logits[keep].min() if keep.any()
                     else sorted_logits[0])
        logits = np.where(logits < threshold, NEG_INF, logits)
    probs = _softmax_np(logits)
    return int(rng.choice(logits.shape[-1], p=probs))


def _softmax_np(x: np.ndarray) -> np.ndarray:
    e = np.exp(x - x.max())
    p = e / e.sum()
    # Renormalise exactly — np.random choice requires sum(p) == 1.
    return p / p.sum()
