"""Token sampling: greedy, temperature, top-k, top-p.

Three implementations of the same semantics:

- :func:`sample` — jit-friendly JAX, f32 logits [B, vocab] -> ids [B], one
  shared option set for the whole batch. Used by the reference generation
  loops (models/generate.py).
- :func:`sample_batched` — jit-friendly JAX with **per-row** options and
  per-row PRNG keys. Used inside the continuous-batching scheduler's fused
  decode step (serve/scheduler.py), where every batch row belongs to a
  different request: sampling on-device shrinks the per-tick device->host
  transfer from the full [B, vocab] logits to B int32 tokens — the
  difference between ~92 ms and ~3.5 ms per tick on a tunneled TPU host.
- :func:`sample_np` — host-side numpy over a single row; the hermetic
  reference oracle for the device samplers' filtering semantics.

The option set mirrors what the Ollama contract exposes via ``options``
(serve/backend.py GenerateOptions), so server-side sampling is a drop-in
for what the reference delegated to Ollama.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .layers import NEG_INF


def greedy(logits: jax.Array) -> jax.Array:
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def _apply_top_k(logits: jax.Array, top_k: int) -> jax.Array:
    if top_k <= 0:
        return logits
    k = min(top_k, logits.shape[-1])
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, NEG_INF, logits)


def _apply_top_p(logits: jax.Array, top_p: float) -> jax.Array:
    if top_p >= 1.0:
        return logits
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # Keep the smallest prefix with cumulative prob >= top_p (always >= 1
    # tok — the explicit set makes that hold even for top_p <= 0).
    keep = cum - probs < top_p
    keep = keep.at[..., 0].set(True)
    threshold = jnp.min(jnp.where(keep, sorted_logits, jnp.inf), axis=-1,
                        keepdims=True)
    return jnp.where(logits < threshold, NEG_INF, logits)


def sample(logits: jax.Array, key: jax.Array, temperature: float = 0.0,
           top_k: int = 0, top_p: float = 1.0) -> jax.Array:
    """Sample next tokens. temperature<=0 means greedy (matching Ollama's
    deterministic mode)."""
    if temperature <= 0.0:
        return greedy(logits)
    logits = logits / jnp.asarray(temperature, logits.dtype)
    logits = _apply_top_k(logits, top_k)
    logits = _apply_top_p(logits, top_p)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def apply_repeat_penalty(logits: jax.Array, ring: jax.Array,
                         rp: jax.Array) -> jax.Array:
    """Ollama-style repetition penalty over a recent-token ring.

    logits: [B,V]; ring: [B,R] recent token ids (entries >= V are empty
    slots and drop out of the scatter); rp: [B] penalty (1.0 = identity).
    Tokens present in the ring have positive logits divided by rp and
    negative logits multiplied by rp — Ollama/CTRL semantics. Must run
    BEFORE top-k/top-p: the penalty reorders candidates."""
    B, V = logits.shape
    mask = jnp.zeros((B, V), bool).at[
        jnp.arange(B)[:, None], ring].set(True, mode="drop")
    rp = rp[:, None]
    pen = jnp.where(logits > 0, logits / rp, logits * rp)
    return jnp.where(mask, pen, logits)


def _warp(sorted_logits: jax.Array, temperature: jax.Array,
          top_k: jax.Array, top_p: jax.Array) -> jax.Array:
    """Shared per-row warping over a descending top-c candidate axis:
    temperature, then top-k, then top-p on the renormalised distribution
    (the filter order of :func:`sample` / :func:`sample_np`). temperature/
    top_k/top_p are [B] and broadcast over any middle axes of
    ``sorted_logits`` [B, ..., C]. Returns warped probabilities.

    One implementation on purpose: :func:`sample_batched` (the decode
    tick) and :func:`spec_verify_batched` (speculative acceptance) MUST
    warp identically or speculative sampling stops matching sequential
    sampling's distribution."""
    extra = sorted_logits.ndim - 2
    def bx(v):          # [B] -> [B, 1..., 1] matching sorted_logits
        return v.reshape(v.shape[0], *([1] * extra), 1)
    C = sorted_logits.shape[-1]
    ranks = jnp.arange(C)
    keep_k = (bx(top_k) <= 0) | (ranks < bx(top_k))
    temp = jnp.maximum(bx(temperature), 1e-6)
    k_masked = jnp.where(keep_k, sorted_logits / temp, NEG_INF)
    probs = jax.nn.softmax(k_masked, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_p = (bx(top_p) >= 1.0) | ((cum - probs) < bx(top_p))
    keep = (keep_k & keep_p).at[..., 0].set(True)     # never empty
    return jax.nn.softmax(jnp.where(keep, sorted_logits / temp, NEG_INF),
                          axis=-1)


def sample_batched(logits: jax.Array, keys: jax.Array, temperature: jax.Array,
                   top_k: jax.Array, top_p: jax.Array,
                   top_c: int = 64, ring: Optional[jax.Array] = None,
                   rp: Optional[jax.Array] = None
                   ) -> tuple[jax.Array, jax.Array]:
    """Per-row sampling: logits [B,V] f32, keys [B,2] (one PRNG key per
    row), temperature/top_k/top_p [B]. Returns (tokens [B] int32,
    advanced keys [B,2]).

    Same filters as :func:`sample` / :func:`sample_np`, vectorised over
    per-row option values: temperature<=0 is greedy; top_k<=0 disables
    top-k; top_p>=1 disables top-p; top_p<=0 degrades to top-1.

    Runs inside the fused decode step, so it must be cheap on the hot
    path: candidates are truncated to the ``top_c`` highest logits via
    ``lax.top_k`` instead of a full-vocab sort (a 32×128k argsort costs
    more than the whole decode step on TPU). Exact when the vocab fits in
    ``top_c`` or the caller's top_k is <= top_c; otherwise the (numerically
    negligible) tail mass past the top-64 candidates is dropped — the
    standard TPU-serving truncation. Two minor divergences from sample_np:
    per-row dynamic k keeps exactly k tokens (ties at the k-th value break
    by sort order), and sampling never leaves the top-``top_c`` set.
    """
    B, V = logits.shape
    if ring is not None:
        logits = apply_repeat_penalty(logits, ring, rp)
    C = min(top_c, V)
    sorted_logits, order = jax.lax.top_k(logits, C)        # [B,C] descending
    wprobs = _warp(sorted_logits, temperature, top_k, top_p)

    split = jax.vmap(lambda k: jax.random.split(k, 2))(keys)   # [B,2,2]
    new_keys, subs = split[:, 0], split[:, 1]
    choice = jax.vmap(jax.random.categorical)(
        subs, jnp.where(wprobs > 0, jnp.log(wprobs), NEG_INF)) # [B] ranks
    sampled = jnp.take_along_axis(order, choice[:, None], axis=-1)[:, 0]
    tok = jnp.where(temperature <= 0.0,
                    jnp.argmax(logits, axis=-1), sampled).astype(jnp.int32)
    return tok, new_keys


def sample_step_batched(logits: jax.Array, keys: jax.Array,
                        temperature: jax.Array, top_k: jax.Array,
                        top_p: jax.Array, *, ring: jax.Array, rp: jax.Array,
                        emit_pos: jax.Array, active: jax.Array,
                        top_c: int = 64
                        ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One decode tick's sample + penalty-ring update, scan-carry shaped.

    The fused multi-step decode path (models/llama.decode_fused) carries
    (keys, ring) through a ``lax.scan`` and the plain one-step decode
    program applies the identical ops once — both MUST route through this
    single implementation, or the fused path's bit-identity-to-K-plain-
    ticks contract (serve/scheduler.py) silently breaks the first time
    one copy drifts.

    logits: [B,V] f32; keys/temperature/top_k/top_p/rp: [B] per-row
    state; ring: [B,R] recent-token penalty window; emit_pos: [B]
    absolute context position of the emitted token (pre-advance lengths
    + 1 — the caller computes it BEFORE the decode step advances
    lengths); active: [B] — parked rows' ring writes drop via the
    out-of-range column sentinel, and their key still splits (the same
    unconditional split the plain program always did, so fused and
    plain key streams agree row-for-row).

    Returns (tokens [B] int32, advanced keys [B,2], updated ring [B,R]).
    """
    toks, keys = sample_batched(logits, keys, temperature, top_k, top_p,
                                top_c=top_c, ring=ring, rp=rp)
    B, R = ring.shape
    idx = jnp.where(active, emit_pos % R, R)
    ring = ring.at[jnp.arange(B), idx].set(toks, mode="drop")
    return toks, keys, ring


def spec_verify_batched(logits: jax.Array, drafts: jax.Array,
                        keys: jax.Array, temperature: jax.Array,
                        top_k: jax.Array, top_p: jax.Array,
                        max_accept: jax.Array,
                        top_c: int = 64, ring: Optional[jax.Array] = None,
                        rp: Optional[jax.Array] = None,
                        ctx_len: Optional[jax.Array] = None
                        ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Speculative-decoding acceptance over one verify pass.

    logits: [B,S,V] f32 from models.llama.verify_step (position j is the
    model's distribution AFTER input j); drafts: [B,S-1] proposed tokens
    (the inputs at positions 1..S-1); keys/temperature/top_k/top_p: [B]
    per-row sampling state (serve/scheduler.py); max_accept: [B] budget
    cap (0..S-1).

    The draft distribution q is a point mass (prompt-lookup drafting), so
    exact speculative sampling reduces to: accept draft_j with
    probability p_warped(draft_j); on first rejection sample the
    replacement from p with the draft token removed and renormalised; if
    every draft is accepted, sample the bonus token from the final
    position's distribution unmodified. Greedy rows (temperature<=0)
    accept while draft == argmax and correct with the argmax — bit-exact
    with the sequential greedy loop. The warped distribution (same
    temperature/top-k/top-p filters and the same ``top_c`` truncation as
    :func:`sample_batched`) is what acceptance and residual sampling use,
    so the emitted stream is distributed exactly as sequential sampling.

    Returns (accepted [B] int32 in [0, S-1], correction [B] int32 — the
    token at stream position ``accepted`` —, advanced keys [B,2]).
    """
    B, S, V = logits.shape
    K = S - 1
    if ring is not None:
        # Per-position recent window with exact SLIDING semantics:
        # sequential sampling at stream position j penalises the last
        # ``Rw`` tokens of (context + drafts[:j]) — each hypothetical
        # draft both ENTERS the window and EVICTS the oldest ring token
        # (the one at ring slot (ctx_len + i) % Rw, which holds context
        # position ctx_len + i - Rw). Occurrence COUNTS (not set union)
        # make eviction correct when a token also occurs elsewhere in
        # the window. ``ctx_len`` [B]: context length before this tick's
        # input token's position (the scheduler's pre-advance lengths).
        Rw = ring.shape[1]
        in_cnt = jnp.zeros((B, V), jnp.float32).at[
            jnp.arange(B)[:, None], ring].add(1.0, mode="drop")
        cnt = jnp.broadcast_to(in_cnt[:, None], (B, S, V))
        if K > 0:
            shifts = jnp.arange(1, K + 1)[None, :]              # [1,K]
            ev_slots = (ctx_len[:, None] + shifts) % Rw         # [B,K]
            ev = jnp.take_along_axis(ring, ev_slots, axis=1)    # [B,K]
            zero = jnp.zeros((B, 1, V), jnp.float32)
            # one_hot of the empty-slot sentinel (>= V) is all-zero, so
            # not-yet-full rings evict nothing.
            ev_pref = jnp.concatenate(
                [zero, jnp.cumsum(jax.nn.one_hot(ev, V,
                                                 dtype=jnp.float32), 1)], 1)
            dr_pref = jnp.concatenate(
                [zero, jnp.cumsum(jax.nn.one_hot(drafts, V,
                                                 dtype=jnp.float32), 1)], 1)
            cnt = cnt - ev_pref + dr_pref                       # [B,S,V]
        member = cnt > 0.5
        rp_b = rp[:, None, None]
        pen = jnp.where(logits > 0, logits / rp_b, logits * rp_b)
        logits = jnp.where(member, pen, logits)
    C = min(top_c, V)
    flat = logits.reshape(B * S, V)
    sorted_logits, order = jax.lax.top_k(flat, C)          # [B*S,C]
    sorted_logits = sorted_logits.reshape(B, S, C)
    order = order.reshape(B, S, C)
    wprobs = _warp(sorted_logits, temperature, top_k, top_p)  # [B,S,C]

    # Per-row keys -> carried key + one dedicated correction key + one
    # acceptance-uniform key per draft position. The correction key MUST
    # be distinct from the rejecting position's uniform key: reusing it
    # correlates the rejection event with the resample and skews the
    # residual distribution.
    split = jax.vmap(lambda k: jax.random.split(k, K + 2))(keys)  # [B,K+2,2]
    new_keys, corr_key, subs = split[:, 0], split[:, 1], split[:, 2:]

    greedy_row = (temperature <= 0.0)[:, None]                    # [B,1]
    argmax_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)    # [B,S]

    # Acceptance per draft position j (draft_j is scored by logits[:, j]).
    dmatch = order[:, :K] == drafts[:, :, None]                   # [B,K,C]
    p_draft = jnp.sum(jnp.where(dmatch, wprobs[:, :K], 0.0), -1)  # [B,K]
    u = jax.vmap(jax.vmap(jax.random.uniform))(subs)              # [B,K]
    ok = jnp.where(greedy_row, drafts == argmax_tok[:, :K], u < p_draft)
    ok &= jnp.arange(K)[None, :] < max_accept[:, None]
    accepted = jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=1), axis=1)

    # Correction at stream position `accepted`. The residual (draft token
    # removed, renormalised) applies ONLY when the stop was a
    # *probabilistic* rejection (accepted < max_accept: the accept test
    # actually ran and failed there). A stop forced by the budget cap —
    # including the zero-filled drafts of undrafted rows (max_accept=0) —
    # or the all-accepted bonus position was never tested, so its token
    # samples from the unmodified warped distribution: removing an
    # untested token would skew the stream (and can zero out a top_k=1
    # row's whole distribution).
    j = accepted[:, None, None]                                   # [B,1,1]
    probs_j = jnp.take_along_axis(wprobs, j, axis=1)[:, 0]        # [B,C]
    order_j = jnp.take_along_axis(order, j, axis=1)[:, 0]         # [B,C]
    prob_rejected = accepted < jnp.minimum(max_accept, K)
    # Rejected-draft token of this position (only defined when accepted<K).
    dr = jnp.take_along_axis(drafts, jnp.minimum(accepted, K - 1)[:, None],
                             axis=1)[:, 0] if K > 0 else jnp.zeros(
                                 (B,), jnp.int32)
    drop = (order_j == dr[:, None]) & prob_rejected[:, None]
    resid = jnp.where(drop, 0.0, probs_j)
    resid = resid / jnp.maximum(resid.sum(-1, keepdims=True), 1e-20)
    choice = jax.vmap(jax.random.categorical)(
        corr_key, jnp.where(resid > 0, jnp.log(resid), NEG_INF))
    sampled = jnp.take_along_axis(order_j, choice[:, None], -1)[:, 0]
    g_corr = jnp.take_along_axis(argmax_tok, accepted[:, None], -1)[:, 0]
    correction = jnp.where(greedy_row[:, 0], g_corr, sampled).astype(jnp.int32)
    return accepted.astype(jnp.int32), correction, new_keys


def spec_verify_tree(logits: jax.Array, drafts: jax.Array,
                     sib_tok: jax.Array, sib_node: jax.Array,
                     keys: jax.Array, temperature: jax.Array,
                     top_k: jax.Array, top_p: jax.Array,
                     max_accept: jax.Array,
                     top_c: int = 64, ring: Optional[jax.Array] = None,
                     rp: Optional[jax.Array] = None,
                     ctx_len: Optional[jax.Array] = None
                     ) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Tree-speculation acceptance: linear main chain + top-2 sibling
    LEAVES, one verify dispatch (serve/scheduler.py tree spec tick).

    logits: [B,N,V] f32 from models.llama.verify_tree — node 0 is the
    root (current token), nodes 1..K the main greedy draft chain, nodes
    K+1..N-1 sibling leaves; node j's row is the model's distribution
    AFTER consuming node j's token along its ancestor path. drafts:
    [B,K] main-chain tokens (inputs at nodes 1..K). sib_tok/sib_node:
    [B,K] — the drafter's second-choice token for main position j and
    the tree node index it occupies (-1 = no sibling budgeted there).

    Main-chain acceptance is EXACTLY :func:`spec_verify_batched` over
    logits[:, :K+1]. At the first probabilistic rejection a0, the
    sibling at that position (if any) gets one more exact multi-round
    test: greedy rows accept it iff it IS the argmax (in which case the
    correction comes from the sibling node's own distribution — bit-
    identical to what the next sequential tick would emit); sampled
    rows accept it with the exact residual probability
    p(sib)/(1 - p(draft)) (point-mass proposals, sib != draft by top-2
    distinctness), and on acceptance the correction samples from the
    sibling node's own warped distribution unmodified. If the sibling
    also rejects, the correction resamples from position a0 with BOTH
    the draft and the sibling removed and renormalised — still the
    exact residual. Repeat-penalty counts follow the accepted path
    (context + drafts[:a0] [+ sib]), reusing the linear eviction/draft
    prefix algebra.

    NOTE: the carried key stream differs from :func:`spec_verify_batched`
    (one extra sibling-uniform split), so sampled streams tree-on vs
    tree-off are differently-random but identically-distributed; greedy
    streams are bit-identical.

    Returns (accepted [B] int32 — total accepted tokens INCLUDING a
    used sibling —, used_sib [B] int32 0/1, correction [B] int32,
    advanced keys [B,2]).
    """
    B, N, V = logits.shape
    K = drafts.shape[1]
    main = logits[:, :K + 1]
    ev_pref = dr_pref = None
    if ring is not None:
        # Identical sliding-window algebra to spec_verify_batched over
        # the main chain; ev_pref/dr_pref are kept for the sibling leg.
        Rw = ring.shape[1]
        in_cnt = jnp.zeros((B, V), jnp.float32).at[
            jnp.arange(B)[:, None], ring].add(1.0, mode="drop")
        cnt = jnp.broadcast_to(in_cnt[:, None], (B, K + 1, V))
        shifts = jnp.arange(1, K + 1)[None, :]
        ev_slots = (ctx_len[:, None] + shifts) % Rw
        ev = jnp.take_along_axis(ring, ev_slots, axis=1)
        zero = jnp.zeros((B, 1, V), jnp.float32)
        ev_pref = jnp.concatenate(
            [zero, jnp.cumsum(jax.nn.one_hot(ev, V, dtype=jnp.float32),
                              1)], 1)
        dr_pref = jnp.concatenate(
            [zero, jnp.cumsum(jax.nn.one_hot(drafts, V,
                                             dtype=jnp.float32), 1)], 1)
        cnt = cnt - ev_pref + dr_pref
        member = cnt > 0.5
        rp_b = rp[:, None, None]
        pen = jnp.where(main > 0, main / rp_b, main * rp_b)
        main = jnp.where(member, pen, main)
    C = min(top_c, V)
    flat = main.reshape(B * (K + 1), V)
    sorted_logits, order = jax.lax.top_k(flat, C)
    sorted_logits = sorted_logits.reshape(B, K + 1, C)
    order = order.reshape(B, K + 1, C)
    wprobs = _warp(sorted_logits, temperature, top_k, top_p)

    # carried key + correction key + sibling-uniform key + K acceptance
    # uniforms (one extra split vs the linear path).
    split = jax.vmap(lambda k: jax.random.split(k, K + 3))(keys)
    new_keys, corr_key, sib_key = split[:, 0], split[:, 1], split[:, 2]
    subs = split[:, 3:]

    greedy_row = (temperature <= 0.0)[:, None]
    argmax_tok = jnp.argmax(main, axis=-1).astype(jnp.int32)      # [B,K+1]

    dmatch = order[:, :K] == drafts[:, :, None]
    p_draft = jnp.sum(jnp.where(dmatch, wprobs[:, :K], 0.0), -1)  # [B,K]
    u = jax.vmap(jax.vmap(jax.random.uniform))(subs)
    ok = jnp.where(greedy_row, drafts == argmax_tok[:, :K], u < p_draft)
    ok &= jnp.arange(K)[None, :] < max_accept[:, None]
    a0 = jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=1), axis=1)

    j = a0[:, None, None]
    probs_j = jnp.take_along_axis(wprobs, j, axis=1)[:, 0]        # [B,C]
    order_j = jnp.take_along_axis(order, j, axis=1)[:, 0]         # [B,C]
    prob_rejected = a0 < jnp.minimum(max_accept, K)
    m = jnp.minimum(a0, K - 1)[:, None]
    dr = jnp.take_along_axis(drafts, m, axis=1)[:, 0]
    st = jnp.take_along_axis(sib_tok, m, axis=1)[:, 0]
    sn = jnp.take_along_axis(sib_node, m, axis=1)[:, 0]
    has_sib = prob_rejected & (sn >= 0)

    # Sibling test — exact residual round. Greedy: the sibling is usable
    # iff it IS the penalised argmax at the rejected position (then the
    # emitted token equals what linear's correction would have been, and
    # we gain its follow-up from the sibling node's own logits).
    g_tok = jnp.take_along_axis(argmax_tok, a0[:, None], -1)[:, 0]
    p_rej = jnp.take_along_axis(p_draft, m, axis=1)[:, 0]
    p_sib = jnp.sum(jnp.where(order_j == st[:, None], probs_j, 0.0), -1)
    ratio = jnp.minimum(p_sib / jnp.maximum(1.0 - p_rej, 1e-20), 1.0)
    u_sib = jax.vmap(jax.random.uniform)(sib_key)
    sib_ok = jnp.where(greedy_row[:, 0], st == g_tok, u_sib < ratio)
    used_sib = has_sib & sib_ok

    # Sibling node's own distribution (the correction after accepting
    # the sibling): penalty counts for the path context + drafts[:a0] +
    # [sib] reuse the main chain's eviction/draft prefixes (a0+1 <= K
    # whenever has_sib, so the gathers stay in range).
    sib_logits = jnp.take_along_axis(
        logits, jnp.clip(sn, 0, N - 1)[:, None, None], axis=1)[:, 0]
    if ring is not None:
        a1 = jnp.minimum(a0 + 1, K)[:, None, None]
        ev_s = jnp.take_along_axis(ev_pref, a1, axis=1)[:, 0]     # [B,V]
        dr_s = jnp.take_along_axis(dr_pref, a0[:, None, None],
                                   axis=1)[:, 0]
        cnt_s = (in_cnt - ev_s + dr_s
                 + jax.nn.one_hot(st, V, dtype=jnp.float32))
        rp_c = rp[:, None]
        pen_s = jnp.where(sib_logits > 0, sib_logits / rp_c,
                          sib_logits * rp_c)
        sib_logits = jnp.where(cnt_s > 0.5, pen_s, sib_logits)
    sorted_sib, order_sib = jax.lax.top_k(sib_logits, C)
    wprobs_sib = _warp(sorted_sib, temperature, top_k, top_p)

    # Correction. Not-used-sib: linear residual at a0 with the draft
    # removed (when probabilistically rejected) and the sibling ALSO
    # removed when it was tested and failed. Used-sib: the sibling
    # node's warped distribution, unmodified (nothing was tested there).
    drop = ((order_j == dr[:, None]) & prob_rejected[:, None]
            | (order_j == st[:, None]) & (has_sib & ~sib_ok)[:, None])
    resid = jnp.where(drop, 0.0, probs_j)
    probs_f = jnp.where(used_sib[:, None], wprobs_sib, resid)
    order_f = jnp.where(used_sib[:, None], order_sib, order_j)
    probs_f = probs_f / jnp.maximum(probs_f.sum(-1, keepdims=True), 1e-20)
    choice = jax.vmap(jax.random.categorical)(
        corr_key, jnp.where(probs_f > 0, jnp.log(probs_f), NEG_INF))
    sampled = jnp.take_along_axis(order_f, choice[:, None], -1)[:, 0]
    g_corr = jnp.where(used_sib,
                       jnp.argmax(sib_logits, axis=-1).astype(jnp.int32),
                       g_tok)
    correction = jnp.where(greedy_row[:, 0], g_corr,
                           sampled).astype(jnp.int32)
    accepted = a0 + used_sib.astype(jnp.int32)
    return (accepted.astype(jnp.int32), used_sib.astype(jnp.int32),
            correction, new_keys)


def sample_np(logits: np.ndarray, rng: np.random.Generator,
              temperature: float = 0.0, top_k: int = 0,
              top_p: float = 1.0, recent=None,
              repeat_penalty: float = 1.0) -> int:
    """Numpy twin of :func:`sample` for one row of logits [vocab].

    Same filtering semantics: temperature<=0 is greedy; top-k keeps the k
    highest logits (ties at the k-th value survive, like lax.top_k's
    threshold compare); top-p keeps the smallest probability-sorted prefix
    whose cumulative mass reaches top_p (always at least one token).
    ``recent``/``repeat_penalty`` mirror :func:`apply_repeat_penalty`.
    """
    # float64 throughout: Generator.choice checks sum(p)==1 to float64
    # tolerance, which float32 softmax fails at real vocab sizes (~128k).
    logits = np.asarray(logits, np.float64)
    if recent is not None and repeat_penalty != 1.0:
        for t in set(int(x) for x in recent):
            if 0 <= t < logits.shape[-1]:
                logits[t] = (logits[t] / repeat_penalty if logits[t] > 0
                             else logits[t] * repeat_penalty)
    if temperature <= 0.0:
        return int(np.argmax(logits))
    logits = logits / max(temperature, 1e-6)
    if top_k > 0:
        k = min(top_k, logits.shape[-1])
        kth = np.sort(logits)[-k]
        logits = np.where(logits < kth, NEG_INF, logits)
    if top_p < 1.0:
        order = np.argsort(logits)[::-1]
        sorted_logits = logits[order]
        probs = _softmax_np(sorted_logits)
        cum = np.cumsum(probs)
        keep = (cum - probs) < top_p
        # top_p <= 0 keeps nothing under the strict compare; degrade to
        # top-1 like the JAX twin (threshold=inf keeps only the max).
        threshold = (sorted_logits[keep].min() if keep.any()
                     else sorted_logits[0])
        logits = np.where(logits < threshold, NEG_INF, logits)
    probs = _softmax_np(logits)
    return int(rng.choice(logits.shape[-1], p=probs))


def _softmax_np(x: np.ndarray) -> np.ndarray:
    e = np.exp(x - x.max())
    p = e / e.sum()
    # Renormalise exactly — np.random choice requires sum(p) == 1.
    return p / p.sum()
