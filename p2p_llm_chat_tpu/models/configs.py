"""Model configurations.

Sizes follow the published architectures for the model families named in
BASELINE.json (llama3.1 tags served via Ollama in the reference —
README.md:52, web/streamlit_app.py:28 — and Mixtral-8x7B for config 5).
``tiny``/``tiny-moe`` are test/CI sizes exercising the exact same code.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Optional


@dataclass(frozen=True)
class RopeScaling:
    """llama3.1-style NTK-by-parts rope scaling."""

    factor: float = 8.0
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_position: int = 8192


@dataclass(frozen=True)
class ModelConfig:
    name: str
    vocab_size: int
    hidden_size: int
    intermediate_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    rope_scaling: Optional[RopeScaling] = None
    rms_norm_eps: float = 1e-5
    tie_embeddings: bool = False
    # MoE (0 experts => dense MLP)
    num_experts: int = 0
    num_experts_per_tok: int = 0
    # GShard-style per-expert capacity factor for large prefill chunks:
    # bucket C = factor * tokens * k / num_experts. None = exact/dropless
    # (models/mixtral.py moe_mlp; decode is always exact).
    moe_capacity_factor: Optional[float] = None
    # token ids (llama3 defaults; byte tokenizer overrides)
    bos_token_id: int = 128000
    eos_token_ids: tuple[int, ...] = (128001, 128008, 128009)

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    @property
    def q_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.num_kv_heads * self.head_dim

    def with_(self, **kw) -> "ModelConfig":
        return replace(self, **kw)


_LLAMA31_SCALING = RopeScaling(factor=8.0, low_freq_factor=1.0,
                               high_freq_factor=4.0, original_max_position=8192)

CONFIGS: dict[str, ModelConfig] = {}


def _register(cfg: ModelConfig) -> ModelConfig:
    CONFIGS[cfg.name] = cfg
    return cfg


# -- llama family ------------------------------------------------------------

_register(ModelConfig(
    name="llama3.1-8b", vocab_size=128256, hidden_size=4096,
    intermediate_size=14336, num_layers=32, num_heads=32, num_kv_heads=8,
    head_dim=128, rope_theta=500000.0, rope_scaling=_LLAMA31_SCALING,
))

_register(ModelConfig(
    name="llama3.1-70b", vocab_size=128256, hidden_size=8192,
    intermediate_size=28672, num_layers=80, num_heads=64, num_kv_heads=8,
    head_dim=128, rope_theta=500000.0, rope_scaling=_LLAMA31_SCALING,
))

_register(ModelConfig(
    name="llama3.2-1b", vocab_size=128256, hidden_size=2048,
    intermediate_size=8192, num_layers=16, num_heads=32, num_kv_heads=8,
    head_dim=64, rope_theta=500000.0, rope_scaling=RopeScaling(factor=32.0),
    tie_embeddings=True,
))

_register(ModelConfig(
    name="llama3.2-3b", vocab_size=128256, hidden_size=3072,
    intermediate_size=8192, num_layers=28, num_heads=24, num_kv_heads=8,
    head_dim=128, rope_theta=500000.0, rope_scaling=RopeScaling(factor=32.0),
    tie_embeddings=True,
))

# -- Mixtral -----------------------------------------------------------------

_register(ModelConfig(
    name="mixtral-8x7b", vocab_size=32000, hidden_size=4096,
    intermediate_size=14336, num_layers=32, num_heads=32, num_kv_heads=8,
    head_dim=128, rope_theta=1e6, num_experts=8, num_experts_per_tok=2,
    moe_capacity_factor=2.0,
    bos_token_id=1, eos_token_ids=(2,), max_seq_len=32768,
))

# ~7.3B-total MoE config for single-chip benching at REAL expert scale:
# each expert is 3*4096*11520 ≈ 141.6M params — 16.4x bench-moe's 8.65M,
# Mixtral-8x7B-class expert width at Mixtral's 8-expert top-2 routing —
# with depth cut to 6 layers so the streamed quantized load fits a 16 GB
# chip next to its KV pool (int8 ≈ 7.3 GB, int4 ≈ 3.9 GB incl. group
# scales; 32 layers of these experts would be a 37B model, BASELINE.json
# config-5 territory — multi-chip). The per-layer MoE arithmetic the
# round-18 bench measures (expert weight streaming, wgu_e fusion,
# dispatch overheads) is layer-count-invariant, so 6 honest layers beat
# 32 unloadable ones. intermediate 11520 = 45*256 = 90*128: divisible
# for the expert-stripe kernels in BOTH int4 groupings (group 256 at
# ng=45 — the odd-count segment walk — and group 128 at ng=90), and by
# every w8a16 block candidate via 128.
_register(ModelConfig(
    name="mixtral-large", vocab_size=32000, hidden_size=4096,
    intermediate_size=11520, num_layers=6, num_heads=32, num_kv_heads=8,
    head_dim=128, rope_theta=1e6, num_experts=8, num_experts_per_tok=2,
    moe_capacity_factor=2.0,
    bos_token_id=1, eos_token_ids=(2,), max_seq_len=8192,
))

# -- test sizes (same code paths, CI-sized) ----------------------------------

_register(ModelConfig(
    name="tiny", vocab_size=512, hidden_size=128, intermediate_size=256,
    num_layers=2, num_heads=4, num_kv_heads=2, head_dim=32, max_seq_len=256,
    rope_theta=10000.0, bos_token_id=1, eos_token_ids=(2,),
))

_register(ModelConfig(
    name="tiny-moe", vocab_size=512, hidden_size=128, intermediate_size=256,
    num_layers=2, num_heads=4, num_kv_heads=2, head_dim=32, max_seq_len=256,
    rope_theta=10000.0, num_experts=4, num_experts_per_tok=2,
    bos_token_id=1, eos_token_ids=(2,),
))

# Loadgen CPU profile: ``tiny`` dims with a real context window, so the
# e2e long-context scenario (docs/loadtest.md) prefills thousands of
# tokens through chunked admission on CPU-class hosts instead of
# truncating at tiny's 256.
_register(ModelConfig(
    name="tiny-long", vocab_size=512, hidden_size=128,
    intermediate_size=256, num_layers=2, num_heads=4, num_kv_heads=2,
    head_dim=32, max_seq_len=4096, rope_theta=10000.0,
    bos_token_id=1, eos_token_ids=(2,),
))

# Like ``tiny`` but every tp-sharded dim (heads, KV heads, mlp, vocab)
# divides a tp=4 mesh: the multi-chip dryrun validates SHARDED wk/wv/KV
# paths with it — `tiny`'s 2 kv heads at tp=4 silently fall back to
# replication (parallel/sharding.constrain), which would leave the
# sharded-KV path unexercised (the production 8B/70B configs' 8 kv heads
# divide their meshes).
_register(ModelConfig(
    name="tiny-tp", vocab_size=512, hidden_size=128, intermediate_size=256,
    num_layers=2, num_heads=4, num_kv_heads=4, head_dim=32, max_seq_len=256,
    rope_theta=10000.0, bos_token_id=1, eos_token_ids=(2,),
))

# ~1B-class dense config used by bench.py on a single v5e chip (fits HBM in
# bf16 with room for KV cache; same architecture family as the 8B).
# max_seq_len 16384: the long-context bench rows (BENCH_CTX 4k-12k,
# round-5) need headroom past the old 2048 cap; rope_theta 500000 (the
# llama3 base) is stable at these lengths, and actual KV allocation is
# sized per run (BENCH_MAX_SEQ / the scheduler's right-sized pool), so
# the cap costs nothing when unused.
_register(ModelConfig(
    name="bench-1b", vocab_size=32768, hidden_size=2048,
    intermediate_size=5632, num_layers=22, num_heads=16, num_kv_heads=8,
    head_dim=128, max_seq_len=16384, rope_theta=500000.0,
    bos_token_id=1, eos_token_ids=(2,),
))

# ~0.4B-param draft model for draft-target speculative decoding: resident
# alongside a big target on the SAME chip (llama3.1-8b int8 ~8.6 GB +
# this config int8 ~0.45 GB + both KV pools fit one 16 GB v5e), it
# proposes K greedy tokens per spec tick that the target verifies in one
# forward (serve/draft_model.py). vocab matches llama3.1-8b — a drafter
# MUST share its target's vocabulary (draft ids feed the target's verify
# forward directly); pair it with a different-vocab target by cloning
# the config at the target's vocab (`get_config("draft-400m").with_(
# vocab_size=target.vocab_size)` — bench.py's freeform spec phase does
# this for bench-1b). Embeddings are untied so the synthetic quote/
# freeform workloads (models/synth.py) can install their successor-map
# lm_head for CPU tests and benches without real checkpoints.
_register(ModelConfig(
    name="draft-400m", vocab_size=128256, hidden_size=1024,
    intermediate_size=4096, num_layers=16, num_heads=8, num_kv_heads=4,
    head_dim=128, max_seq_len=16384, rope_theta=500000.0,
))

# ~1.2B-param MoE config (8 experts, top-2) for single-chip MoE benching:
# measures the scatter/gather expert-dispatch cost of models/mixtral.py on
# real hardware (BASELINE.json config 5's family; ep=1 on one chip).
_register(ModelConfig(
    name="bench-moe", vocab_size=32768, hidden_size=1024,
    intermediate_size=2816, num_layers=16, num_heads=8, num_kv_heads=4,
    # max_seq 8192 for the round-5 long-context MoE rows (rope_theta 1e6
    # covers it; KV is allocated per run, so the cap is free unused).
    head_dim=128, max_seq_len=8192, rope_theta=1e6,
    num_experts=8, num_experts_per_tok=2, moe_capacity_factor=2.0,
    bos_token_id=1, eos_token_ids=(2,),
))


def get_config(name: str) -> ModelConfig:
    try:
        return CONFIGS[name]
    except KeyError:
        raise KeyError(f"unknown model config {name!r}; have {sorted(CONFIGS)}") from None
