"""llama-family decoder (3.x dense models) — functional JAX, TPU-first.

Replaces the reference's out-of-tree Ollama llama3.1 backend
(web/streamlit_app.py:28, README.md:52) with an in-tree implementation.
Architecture: pre-norm transformer, RMSNorm, RoPE (llama3.1 NTK scaling),
grouped-query attention, SwiGLU MLP, optionally tied embeddings.

TPU-first choices:
- layers stacked on a leading axis, decoder body is one ``lax.scan`` —
  constant-size XLA graph regardless of depth (fast compiles for 80-layer
  70B), and scan keeps weights resident in HBM with no per-layer dispatch.
- dense KV cache ``[L, B, max_seq, Hkv, D]`` with ragged per-row lengths;
  decode writes one slot via a batched scatter and masks by length. (The
  serving engine swaps this for the paged Pallas cache; this dense path is
  the reference implementation and the test oracle.)
- bf16 activations/weights, f32 softmax/norms; one all-reduce per block
  under tensor parallelism (Megatron layout — see parallel/sharding.py).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ..parallel.sharding import LogicalRules, DEFAULT_RULES, constrain
from .configs import ModelConfig
from .quant import LayerSlice, QTensor, QTensor4, mm
from .layers import (
    DEFAULT_COMPUTE_DTYPE,
    apply_rope,
    attend_gqa,
    attend_gqa_auto,
    causal_mask,
    length_mask,
    rms_norm,
    rope_frequencies,
    swiglu,
)


class KVCache(NamedTuple):
    """k/v: [L, B, max_seq, Hkv, D]; lengths: [B] valid slots per row."""

    k: jax.Array
    v: jax.Array
    lengths: jax.Array

    @classmethod
    def create(cls, config: ModelConfig, batch: int, max_seq: int,
               dtype=DEFAULT_COMPUTE_DTYPE) -> "KVCache":
        shape = (config.num_layers, batch, max_seq, config.num_kv_heads,
                 config.head_dim)
        return cls(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
                   lengths=jnp.zeros((batch,), jnp.int32))


# -- parameters ---------------------------------------------------------------

def init_params(config: ModelConfig, key: jax.Array,
                dtype=DEFAULT_COMPUTE_DTYPE) -> dict:
    """Random init (scaled normal). Real weights come from
    models/weights.py; random init serves tests and synthetic benches."""
    ks = jax.random.split(key, 10)
    L, H, E = config.num_layers, config.hidden_size, config.intermediate_size
    std = H ** -0.5

    def normal(k, shape, scale=std):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    params = {
        "embed": normal(ks[0], (config.vocab_size, H), scale=1.0),
        "layers": {
            "attn_norm": jnp.ones((L, H), dtype),
            "wq": normal(ks[1], (L, H, config.q_dim)),
            "wk": normal(ks[2], (L, H, config.kv_dim)),
            "wv": normal(ks[3], (L, H, config.kv_dim)),
            "wo": normal(ks[4], (L, config.q_dim, H)),
            "mlp_norm": jnp.ones((L, H), dtype),
            "w_gate": normal(ks[5], (L, H, E)),
            "w_up": normal(ks[6], (L, H, E)),
            "w_down": normal(ks[7], (L, E, H)),
        },
        "final_norm": jnp.ones((H,), dtype),
    }
    if not config.tie_embeddings:
        params["lm_head"] = normal(ks[8], (H, config.vocab_size))
    return params


def init_params_quantized(config: ModelConfig, key: jax.Array,
                          dtype=DEFAULT_COMPUTE_DTYPE,
                          quant: str = "int8") -> dict:
    """Random init streamed straight into quantized tensors, one layer
    at a time — the bf16 tree is never materialised. ``quant``:
    ``int8`` (per-channel QTensor) or ``int4`` (group-wise QTensor4 —
    packed nibbles, HALF the int8 footprint again; leaves whose
    contraction dim cannot group fall back to int8 per
    quant._quantize_leaf).

    Why: ``init_params`` + ``quantize_params`` peaks at the full bf16
    model (~16 GB for llama3.1-8B), which cannot fit a single v5e chip's
    16 GB HBM even though the int8 model (~8.6 GB with bf16 embeddings)
    plus an int8 KV pool does. This builds the stacked int8 leaves with
    a donated per-layer write loop (one dispatch per layer), so peak
    extra memory is one layer's bf16 leaves (~0.3 GB at 8B).

    The projection pairs are generated ALREADY FUSED (wqkv / wgu —
    models/llama.fuse_params' layout), so ``fuse_params`` is a no-op on
    the result and no second copy of the weights ever exists; the same
    numerics path as fused+quantized serving. Distribution matches
    init_params' scaled normal (different RNG stream). Synthetic-bench /
    random-init serving only — real checkpoints stream through
    models/weights.py.
    """
    from .quant import _quantize_leaf, stream_bufs

    if quant not in ("int8", "int4"):
        raise ValueError(f"quant must be int8|int4, got {quant!r}")
    L, H, E = config.num_layers, config.hidden_size, config.intermediate_size
    std = H ** -0.5
    key, k_embed, k_head = jax.random.split(key, 3)

    def normal(k, shape, scale=std, dt=dtype):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dt)

    dims = {
        "wqkv": (H, config.q_dim + 2 * config.kv_dim),
        "wo": (config.q_dim, H),
        "wgu": (H, 2 * E),
        "w_down": (E, H),
    }
    layers: dict = {
        "attn_norm": jnp.ones((L, H), dtype),
        "mlp_norm": jnp.ones((L, H), dtype),
    }
    for name, (din, dout) in dims.items():
        layers[name] = stream_bufs(L, (din, dout), quant)

    import functools

    @functools.partial(jax.jit, donate_argnums=(0,))
    def write_layer(bufs: dict, k: jax.Array, layer: jax.Array) -> dict:
        ks = jax.random.split(k, len(dims))
        out = dict(bufs)
        for i, (name, (din, dout)) in enumerate(dims.items()):
            qt = _quantize_leaf(normal(ks[i], (din, dout)), quant)
            out[name] = type(qt)(q=bufs[name].q.at[layer].set(qt.q),
                                 s=bufs[name].s.at[layer].set(qt.s))
        return out

    bufs = {name: layers[name] for name in dims}
    layer_keys = jax.random.split(key, L)
    for li in range(L):
        bufs = write_layer(bufs, layer_keys[li], jnp.asarray(li))
    layers.update(bufs)

    params = {
        "embed": normal(k_embed, (config.vocab_size, H), scale=1.0),
        "layers": layers,
        "final_norm": jnp.ones((H,), dtype),
    }
    if not config.tie_embeddings:
        params["lm_head"] = _quantize_leaf(
            normal(k_head, (H, config.vocab_size)), quant)
    jax.block_until_ready(params)
    return params


def fuse_tp_for(config: ModelConfig, mesh: Optional[Mesh]) -> int:
    """Device-block count of the fused-projection column layout under a
    mesh — the single decision point shared by :func:`fuse_params` (which
    builds the layout) and the extraction sites in :func:`_attn_qkv` /
    ``_default_mlp`` (which must unpack the same layout). 1 = the plain
    ``[q | k | v]`` concatenation; ``tp`` = per-device interleaved blocks
    ``[q_0|k_0|v_0 | q_1|k_1|v_1 | ...]`` so sharding the fused column
    axis over tp keeps every device's block exactly its own head/ffn
    columns (a plain concat sharded over tp would split mid-tensor).
    Falls back to 1 when any fused dimension doesn't divide tp (tiny test
    configs; production dims always divide)."""
    if mesh is None or "tp" not in mesh.shape:
        return 1
    t = mesh.shape["tp"]
    if t <= 1:
        return 1
    if (config.num_heads % t or config.num_kv_heads % t
            or config.intermediate_size % t):
        return 1
    return t


def fuse_params(params: dict, tp: int = 1, mesh: Optional[Mesh] = None,
                rules: LogicalRules = DEFAULT_RULES) -> dict:
    """Concatenate per-layer ``wq|wk|wv -> wqkv`` and ``w_gate|w_up ->
    wgu`` so a decode step runs 4 weight matmuls per layer instead of 7.

    Why: decode is HBM-bandwidth-bound, and on a v5e chip the measured
    per-matmul-call fixed cost (kernel entry + tile pipeline fill) is what
    keeps the weight stream below the bandwidth bound — fusing the
    column-parallel pairs cut the measured matmul floor of a bench-1b
    step by ~20% (see BASELINE.md round-3 notes). The math is identical:
    the fused weight's output columns are a permutation of the originals',
    and int8 per-output-channel scales permute with them
    (models/quant.QTensor stores s per output column).

    Under tensor parallelism pass ``tp = fuse_tp_for(config, mesh)`` and
    the mesh: columns interleave as per-device blocks (see fuse_tp_for)
    and the fused leaves are device_put with the fused column axis
    sharded over tp — each device's shard is exactly its own q/k/v (or
    gate/up) columns, so TP serving keeps the fused-matmul win instead
    of giving it up (VERDICT r3 weak #3).

    Works on bf16 arrays and QTensors alike; no-op if already fused.

    CAVEAT: the layout is derived from (config, mesh) at every use site
    (fuse_tp_for), not recorded on the params — running tp-fused params
    through a forward with a DIFFERENT mesh (or none) unpacks the wrong
    interleave and silently scrambles head columns. The serving
    scheduler, the only production composition point, fuses and runs
    under the same mesh object by construction; keep it that way.
    """
    layers = params["layers"]
    if "wqkv" in layers:
        if tp > 1:
            raise ValueError(
                "params are already fused in the plain [q|k|v] layout; "
                "they cannot be re-laid-out for tp>1 (unpacking would "
                "scramble head columns). Fuse from unfused weights under "
                "the mesh instead.")
        return params

    def cat(ws):
        """Interleaved per-device concat: [L, H, C_i] -> per-device
        column blocks [L, H, tp, C_i/tp] concatenated on the block
        axis -> [L, H, sum(C_i)]. tp=1 degenerates to a plain concat."""
        def icat(arrs):
            if tp == 1:
                return jnp.concatenate(arrs, axis=-1)
            blk = [a.reshape(*a.shape[:-1], tp, a.shape[-1] // tp)
                   for a in arrs]
            out = jnp.concatenate(blk, axis=-1)
            return out.reshape(*out.shape[:-2], -1)

        if isinstance(ws[0], (QTensor, QTensor4)):
            # Both precisions concat on the OUT axis: int8 scales ride
            # their columns; int4's packed rows and group scales share
            # the contraction layout, so columns concat the same way.
            return type(ws[0])(q=icat([w.q for w in ws]),
                               s=icat([w.s for w in ws]))
        return icat(ws)

    fuse_mlp = layers["w_gate"].ndim == 3   # dense [L,H,E]
    # MoE 4-D per-expert ffn leaves fuse into "wgu_e" [L,NE,H,2F] on the
    # single-chip path only (models/mixtral.moe_mlp runs gate+up as one
    # batched einsum). Under a mesh they stay separate: the expert axis
    # shards over ("ep","tp") and the ring path (parallel/ring.py
    # moe_ring_mlp_fn) reads w_gate/w_up by name from its local shard.
    fuse_moe = (not fuse_mlp and layers["w_gate"].ndim == 4
                and tp == 1 and mesh is None)
    drop = ("wq", "wk", "wv") + (("w_gate", "w_up")
                                 if (fuse_mlp or fuse_moe) else ())
    fused = {k: v for k, v in layers.items() if k not in drop}
    fused["wqkv"] = cat([layers["wq"], layers["wk"], layers["wv"]])
    if fuse_mlp:
        fused["wgu"] = cat([layers["w_gate"], layers["w_up"]])
    if fuse_moe:
        fused["wgu_e"] = cat([layers["w_gate"], layers["w_up"]])
    if mesh is not None and tp > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P

        tp_ax = rules.get("heads", "tp")
        def put(leaf):
            def put_arr(a):
                spec = [None] * (a.ndim - 1) + [tp_ax]
                return jax.device_put(a, NamedSharding(mesh, P(*spec)))
            if isinstance(leaf, (QTensor, QTensor4)):
                return type(leaf)(q=put_arr(leaf.q), s=put_arr(leaf.s))
            return put_arr(leaf)

        fused["wqkv"] = put(fused["wqkv"])
        if fuse_mlp:
            fused["wgu"] = put(fused["wgu"])
    out = dict(params)
    out["layers"] = fused
    return out


def param_axes(config: ModelConfig) -> dict:
    """Logical-axis tree matching init_params (leading layer axis on stacked
    leaves is unsharded). Feed to parallel.sharding.shard_params."""
    axes = {
        "embed": ("vocab", "embed"),
        "layers": {
            "attn_norm": (None, "embed"),
            "wq": (None, "embed", "heads"),
            "wk": (None, "embed", "kv_heads"),
            "wv": (None, "embed", "kv_heads"),
            "wo": (None, "heads", "embed"),
            "mlp_norm": (None, "embed"),
            "w_gate": (None, "embed", "mlp"),
            "w_up": (None, "embed", "mlp"),
            "w_down": (None, "mlp", "embed"),
        },
        "final_norm": ("embed",),
    }
    if not config.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


# -- forward ------------------------------------------------------------------

def _layer_view(layers: dict, layer: jax.Array) -> dict:
    """One layer's view of the stacked layer tree, for a scan body that
    iterates ``layer`` indices instead of scanning over the weights.

    Why not scan xs: scan's per-iteration slicing of the stacked weights
    materialises each layer's slice before the Pallas w8a16 matmul
    (custom-call operands cannot alias a slice view) — measured at ~1.9 ms
    of a 3.8 ms bench-1b decode step, half the step. Stacked quantized
    matmul weights therefore stay WHOLE here, wrapped as
    :class:`~.quant.LayerSlice` so ``mm`` / ``q_einsum`` feed them to the
    layer-indexed kernels (ops/quant_mm.quant_matmul_stacked and the
    4-D expert twin quant_matmul_experts_stacked — before round-18 the
    expert stacks were sliced eagerly here, which bypassed the Pallas
    path for every MoE expert matmul); everything else (norms, bf16
    weights) is sliced lazily — XLA fuses those slices into their
    consumers for free.
    """
    out = {}
    for k, v in layers.items():
        if isinstance(v, (QTensor, QTensor4)):
            if v.q.ndim >= 3:
                out[k] = LayerSlice(v, layer)
            else:
                out[k] = type(v)(
                    q=jax.lax.dynamic_index_in_dim(v.q, layer, 0, False),
                    s=jax.lax.dynamic_index_in_dim(v.s, layer, 0, False))
        else:
            out[k] = jax.lax.dynamic_index_in_dim(v, layer, 0, False)
    return out


def _default_mlp(x: jax.Array, lp: dict, mesh: Optional[Mesh],
                 rules: LogicalRules,
                 config: Optional[ModelConfig] = None) -> jax.Array:
    if "wgu" in lp:                      # fused gate|up (fuse_params)
        gu = mm(x, lp["wgu"])
        E = gu.shape[-1] // 2
        t = fuse_tp_for(config, mesh) if config is not None else 1
        if t > 1:
            # per-device interleaved fused layout (fuse_tp_for): unpack
            # within each device block; gate/up land in natural order
            # because gate columns are dealt to devices contiguously.
            lead = gu.shape[:-1]
            blk = gu.reshape(*lead, t, 2 * E // t)
            Ed = E // t
            g_, u_ = blk[..., :Ed], blk[..., Ed:]
            gu2 = jax.nn.silu(g_) * u_
            h = gu2.reshape(*lead, E)
            h = constrain(h, mesh, ("batch", None, "act_mlp"), rules)
            return mm(h, lp["w_down"])
        g = jax.nn.silu(gu[..., :E]) * gu[..., E:]
        return mm(g, lp["w_down"])
    return swiglu(x, lp["w_gate"], lp["w_up"], lp["w_down"])


def _attn_qkv(h: jax.Array, lp: dict, config: ModelConfig,
              inv_freq: jax.Array, positions: jax.Array,
              mesh: Optional[Mesh], rules: LogicalRules):
    """Pre-norm + q/k/v projections + rope. h: [B,S,H] -> q [B,S,Hq,D],
    k/v [B,S,Hkv,D]. Shared between the dense and paged block variants."""
    B, S, _ = h.shape
    x = rms_norm(h, lp["attn_norm"], config.rms_norm_eps)
    if "wqkv" in lp:                     # fused q|k|v (fuse_params)
        qkv = mm(x, lp["wqkv"])
        Q, KV = config.q_dim, config.kv_dim
        t = fuse_tp_for(config, mesh)
        if t > 1:
            # per-device interleaved fused layout (fuse_tp_for): unpack
            # within each device block. Heads come out in natural order
            # (head columns are dealt to devices contiguously).
            blk = qkv.reshape(B, S, t, (Q + 2 * KV) // t)
            Qd, KVd = Q // t, KV // t
            q = blk[..., :Qd].reshape(B, S, config.num_heads,
                                      config.head_dim)
            k = blk[..., Qd: Qd + KVd].reshape(B, S, config.num_kv_heads,
                                               config.head_dim)
            v = blk[..., Qd + KVd:].reshape(B, S, config.num_kv_heads,
                                            config.head_dim)
        else:
            q = qkv[..., :Q].reshape(B, S, config.num_heads,
                                     config.head_dim)
            k = qkv[..., Q: Q + KV].reshape(B, S, config.num_kv_heads,
                                            config.head_dim)
            v = qkv[..., Q + KV:].reshape(B, S, config.num_kv_heads,
                                          config.head_dim)
    else:
        q = mm(x, lp["wq"]).reshape(B, S, config.num_heads, config.head_dim)
        k = mm(x, lp["wk"]).reshape(B, S, config.num_kv_heads,
                                    config.head_dim)
        v = mm(x, lp["wv"]).reshape(B, S, config.num_kv_heads,
                                    config.head_dim)
    q = constrain(q, mesh, ("batch", None, "act_heads", None), rules)
    k = constrain(k, mesh, ("batch", None, "act_heads", None), rules)
    q = apply_rope(q, positions, inv_freq)
    k = apply_rope(k, positions, inv_freq)
    return q, k, v


def _post_attn(h: jax.Array, attn: jax.Array, lp: dict, config: ModelConfig,
               mesh: Optional[Mesh], rules: LogicalRules, mlp_fn) -> jax.Array:
    """Output projection + residual + MLP + residual. attn: [B,S,Hq,D]."""
    B, S = attn.shape[:2]
    attn = attn.reshape(B, S, config.q_dim)
    h = h + constrain(mm(attn, lp["wo"]), mesh, ("batch", None, "act_embed"), rules)
    x = rms_norm(h, lp["mlp_norm"], config.rms_norm_eps)
    mlp = (mlp_fn(x, lp, mesh, rules) if mlp_fn is not None
           else _default_mlp(x, lp, mesh, rules, config))
    return h + constrain(mlp, mesh, ("batch", None, "act_embed"), rules)


def _block(h: jax.Array, lp: dict, config: ModelConfig, inv_freq: jax.Array,
           positions: jax.Array, cache_k: jax.Array, cache_v: jax.Array,
           layer: jax.Array, write_pos: jax.Array, mask: jax.Array,
           mesh: Optional[Mesh], rules: LogicalRules,
           kv_window: Optional[int] = None, mlp_fn=None,
           causal0: bool = False):
    """One decoder block against the full stacked cache.

    h: [B,S,H]; cache_k/v: [L,B,max_seq,Hkv,D] (the whole stacked cache —
    this layer's slice is selected by ``layer``); write_pos: [B,S] absolute
    slots to write this step's k/v into; mask: [B or 1, 1, S, max_seq].
    Returns (h, new_cache_k, new_cache_v).

    The cache flows through the layer scan as *carry* and is updated with a
    scatter at exactly the written slots: per step, HBM sees a tiny write
    plus one read of this layer's history — not a rewrite of the stacked
    cache (which scan ys would force), and not a ``rep``× expanded read
    (attend_gqa contracts the unexpanded cache).

    ``mlp_fn(x, lp, mesh, rules)`` swaps the dense SwiGLU for another MLP —
    models/mixtral.py passes its sparse-MoE block here, so the attention/
    cache mechanics exist in exactly one place.
    """
    B, S, _ = h.shape
    q, k, v = _attn_qkv(h, lp, config, inv_freq, positions, mesh, rules)

    # Scatter this step's k/v into the carried cache at (layer, row,
    # write_pos); rows write S consecutive slots, in place. mode="drop":
    # in-bounds for every normal path; the speculative verify_step aims
    # positions past a near-budget row's cache at max_seq on purpose
    # (never-trusted draft slots must not clamp onto the last real slot).
    b_idx = jnp.arange(B)[:, None]
    cache_k = cache_k.at[layer, b_idx, write_pos].set(k, mode="drop")
    cache_v = cache_v.at[layer, b_idx, write_pos].set(v, mode="drop")
    k_layer = jax.lax.dynamic_index_in_dim(cache_k, layer, 0, keepdims=False)
    v_layer = jax.lax.dynamic_index_in_dim(cache_v, layer, 0, keepdims=False)
    if kv_window is not None and kv_window < k_layer.shape[1]:
        # Static attention-read window: every row's live context fits in
        # the first kv_window slots (caller guarantees lengths < window),
        # so HBM reads scale with actual context, not allocated max_seq.
        k_layer = k_layer[:, :kv_window]
        v_layer = v_layer[:, :kv_window]

    # The Pallas causal0 kernel cannot consume mesh-sharded operands
    # (same policy as the quant matmul kernels): under a mesh the XLA
    # flash path shards fine and stays.
    attn = attend_gqa_auto(
        q, k_layer, v_layer, mask,
        causal0_len=S if (causal0 and mesh is None) else None)  # [B,S,H,D]
    return _post_attn(h, attn, lp, config, mesh, rules, mlp_fn), \
        cache_k, cache_v


def hidden_states(params: dict, config: ModelConfig, tokens: jax.Array,
                  positions: jax.Array, cache: KVCache, mask: jax.Array,
                  mesh: Optional[Mesh] = None,
                  rules: LogicalRules = DEFAULT_RULES,
                  kv_window: Optional[int] = None,
                  mlp_fn=None, causal0: bool = False,
                  write_pos: Optional[jax.Array] = None
                  ) -> tuple[jax.Array, KVCache]:
    """embed -> scan(blocks) -> final norm. Returns (h [B,S,H], cache) —
    the shared trunk of :func:`forward`; also the embedding feature
    extractor (:func:`embed_pooled` / the serve /api/embed path).

    ``write_pos`` ([B,S], default = ``positions``): cache slots this
    step's k/v land in, decoupled from the RoPE positions — tree
    speculation (:func:`verify_tree`) writes node j at slot lengths+j
    while its RoPE position is lengths+depth(j)."""
    # Compute dtype follows the params' dtype (bf16 in production; the HF
    # parity tests load f32 weights and get f32 compute for tight tolerances).
    h = params["embed"][tokens]
    h = constrain(h, mesh, ("batch", None, "act_embed"), rules)
    inv_freq = rope_frequencies(config)
    wp = positions if write_pos is None else write_pos

    def body(carry, layer):
        h, ck, cv = carry
        lp = _layer_view(params["layers"], layer)
        h, ck, cv = _block(h, lp, config, inv_freq, positions, ck, cv,
                           layer, wp, mask, mesh, rules, kv_window,
                           mlp_fn, causal0)
        return (h, ck, cv), None

    (h, new_k, new_v), _ = jax.lax.scan(
        body, (h, cache.k, cache.v), jnp.arange(config.num_layers))
    h = rms_norm(h, params["final_norm"], config.rms_norm_eps)
    return h, KVCache(new_k, new_v, cache.lengths)


def forward(params: dict, config: ModelConfig, tokens: jax.Array,
            positions: jax.Array, cache: KVCache, mask: jax.Array,
            mesh: Optional[Mesh] = None,
            rules: LogicalRules = DEFAULT_RULES,
            kv_window: Optional[int] = None,
            mlp_fn=None, causal0: bool = False,
            last_idx: Optional[jax.Array] = None,
            write_pos: Optional[jax.Array] = None,
            ) -> tuple[jax.Array, KVCache]:
    """Shared forward: embed -> scan(blocks) -> norm -> logits.

    tokens/positions: [B,S]; mask: [B or 1,1,S,W] (True = attend) where W
    is ``kv_window`` (or max_seq when unset — the static attention-read
    window; see _block); k/v for this step are written at ``positions`` in
    every layer's cache. Returns (logits [B,S,vocab] f32, updated cache).

    ``last_idx`` ([B] int): gather each row's hidden state at that
    position BEFORE the lm_head and return [B,1,vocab] logits for those
    positions only. Admission sampling needs exactly one position per
    row, and the full-S path materialises an [B*S, vocab] f32 logits
    temp — 3.9 GB (and ~8.6 TFLOP of discarded lm_head compute) at 8B
    dims with a 64x128 admission chunk, which is what OOM'd 64-slot
    serving on a 16 GB chip.
    """
    h, cache = hidden_states(params, config, tokens, positions, cache, mask,
                             mesh, rules, kv_window, mlp_fn, causal0,
                             write_pos=write_pos)
    if last_idx is not None:
        h = jnp.take_along_axis(h, last_idx[:, None, None].astype(jnp.int32),
                                axis=1)                     # [B,1,H]
    lm_head = (params["embed"].T if config.tie_embeddings
               else params["lm_head"])
    logits = mm(h, lm_head).astype(jnp.float32)
    logits = constrain(logits, mesh, ("batch", None, "act_vocab"), rules)
    return logits, cache


def embed_pooled(params: dict, config: ModelConfig, tokens: jax.Array,
                 lens: jax.Array, mesh: Optional[Mesh] = None,
                 rules: LogicalRules = DEFAULT_RULES,
                 mlp_fn=None) -> jax.Array:
    """Sequence embeddings: length-masked mean pool of the final-norm
    hidden states, L2-normalized — the in-tree backend for Ollama's
    ``POST /api/embed`` (the reference delegates all LLM capability to
    Ollama, whose API includes embeddings; serve/api.py).

    tokens: [B,S] right-padded; lens: [B]. Returns [B,H] float32 unit
    vectors; pad positions contribute nothing (masked before pooling).
    """
    B, S = tokens.shape
    cache = KVCache.create(config, B, S, dtype=params["embed"].dtype)
    positions = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))
    mask = causal_mask(S, S, 0)
    h, _ = hidden_states(params, config, tokens, positions, cache, mask,
                         mesh, rules, mlp_fn=mlp_fn)
    h = h.astype(jnp.float32)
    valid = (jnp.arange(S)[None, :] < lens[:, None]).astype(jnp.float32)
    pooled = (h * valid[:, :, None]).sum(axis=1) / jnp.maximum(
        lens[:, None].astype(jnp.float32), 1.0)
    norm = jnp.linalg.norm(pooled, axis=-1, keepdims=True)
    return pooled / jnp.maximum(norm, 1e-9)


def prefill(params: dict, config: ModelConfig, tokens: jax.Array,
            prompt_lens: jax.Array, cache: KVCache,
            mesh: Optional[Mesh] = None,
            rules: LogicalRules = DEFAULT_RULES,
            last_only: bool = False) -> tuple[jax.Array, KVCache]:
    """Process right-padded prompts from position 0.

    tokens: [B,S] right-padded; prompt_lens: [B]. Causal masking makes pad
    slots invisible to real queries (pads sit after the prompt); cache
    lengths are set to prompt_lens so decode never attends to pad slots.
    Returns (logits [B,S,vocab], cache) — or (logits [B,1,vocab] at each
    row's last prompt position, cache) with ``last_only`` (the admission
    shape; see forward's last_idx note).
    """
    B, S = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))
    mask = causal_mask(S, cache.k.shape[2], 0)        # [1,1,S,max_seq]
    # The mask is exactly causal-from-0 over the first S kv slots (pads
    # sit after prompts; slots past S are causally dead), so big shapes
    # may take the Pallas flash-kernel path (layers.attend_gqa_auto).
    logits, cache = forward(params, config, tokens, positions, cache, mask,
                            mesh, rules, causal0=True,
                            last_idx=prompt_lens - 1 if last_only else None)
    return logits, cache._replace(lengths=prompt_lens.astype(jnp.int32))


def prefill_chunk(params: dict, config: ModelConfig, tokens: jax.Array,
                  cache: KVCache, offset: int,
                  mesh: Optional[Mesh] = None,
                  rules: LogicalRules = DEFAULT_RULES,
                  last_idx: Optional[jax.Array] = None,
                  mlp_fn=None) -> tuple[jax.Array, KVCache]:
    """Continuation prefill: C prompt tokens per row at positions
    ``offset .. offset+C``, resuming from a partial KV already in
    ``cache`` — the chunked-admission unit (serve/scheduler.py splits a
    long prompt into fixed token-budget chunks so one admission never
    stalls in-flight decodes for the whole prompt's prefill). The same
    offset-mask continuation shape the prefix-cache prologue and the
    speculative verify path use.

    tokens: [B,C]; each row writes cache slots offset..offset+C and
    attends the FULL cache width under a ``causal_mask(C, W, offset)``
    — deliberately NOT a trimmed ``kv_window``. Masked not-yet-written
    tail keys carry exactly-zero probability, so every softmax/matmul
    reduction runs at the same padded width as the single-shot prefill
    and the emitted KV and logits are BIT-identical to one whole-prompt
    dispatch (a narrower window changes XLA's reduction blocking and
    drifts last bits — measured; pinned by tests/test_chunked_prefill).
    The full-width scores add no FLOPs chunking could have saved: the
    single-shot path computes the same [S, W] score matrix at once.

    ``last_idx`` ([B] int): CHUNK-LOCAL position to gather logits at
    ([B,1,vocab]) — the admission path clamps each row's last prompt
    position into this chunk and keeps the gather only for rows whose
    last position actually falls here. Cache lengths are NOT set; the
    caller installs total lengths atomically with the final chunk so a
    half-prefilled row never looks live.

    Returns (logits [B,1,vocab] (or [B,C,vocab] without last_idx),
    cache with the chunk's slots written, lengths untouched)."""
    B, C = tokens.shape
    positions = jnp.broadcast_to(offset + jnp.arange(C)[None, :], (B, C))
    mask = causal_mask(C, cache.k.shape[2], offset)
    return forward(params, config, tokens, positions, cache, mask, mesh,
                   rules, mlp_fn=mlp_fn, last_idx=last_idx)


def decode_step(params: dict, config: ModelConfig, tokens: jax.Array,
                cache: KVCache, mesh: Optional[Mesh] = None,
                rules: LogicalRules = DEFAULT_RULES,
                active: Optional[jax.Array] = None,
                kv_window: Optional[int] = None) -> tuple[jax.Array, KVCache]:
    """One autoregressive step for every row of the batch.

    tokens: [B,1] (this step's input token per row). Each row writes cache
    slot ``lengths[b]`` and attends to slots [0, lengths[b]].

    ``active`` ([B] bool) parks finished/empty rows for the
    continuous-batching scheduler (serve/scheduler.py): a parked row's
    length does NOT advance, so the step is a no-op for it by the
    overwrite-before-trust invariant — the row still scatters this step's
    (garbage) k/v into slot ``lengths[b]``, but since its length is
    unchanged, the next step that matters for that row writes the same
    slot again before anything attends to it as history. Parked rows'
    logits are garbage and must be ignored by the caller. Rows never read
    or write any other row's slots, so parked rows cannot corrupt active
    ones.

    Returns (logits [B,1,vocab], cache with lengths+1 where active).
    """
    positions = cache.lengths[:, None]                 # [B,1]
    window = kv_window if kv_window is not None else cache.k.shape[2]
    mask = length_mask(window, cache.lengths + 1)      # include slot being written
    logits, cache = forward(params, config, tokens, positions, cache, mask,
                            mesh, rules, kv_window=kv_window)
    inc = jnp.ones_like(cache.lengths) if active is None else active.astype(jnp.int32)
    return logits, cache._replace(lengths=cache.lengths + inc)


def decode_fused(params: dict, config: ModelConfig, tokens: jax.Array,
                 cache, mesh: Optional[Mesh] = None,
                 rules: LogicalRules = DEFAULT_RULES,
                 active: Optional[jax.Array] = None, *,
                 num_steps: int, sample_fn, sample_state, stop_ids,
                 kv_window: Optional[int] = None,
                 pages: Optional[int] = None,
                 interpret: Optional[bool] = None,
                 step_fn=None):
    """``num_steps`` autoregressive steps in ONE dispatch: a ``lax.scan``
    over :func:`decode_step` (dense) / :func:`decode_step_paged`
    (``pages`` set) carrying the cache, the sampled next-token feed, the
    active mask, and the caller's sampling state — so K decode steps cost
    one host dispatch/readback instead of K (the host-side per-dispatch
    overhead was ~a third of every decode tick at B=32; BENCH_r05).

    Each scan step IS the plain step — the same ``decode_step[_paged]``
    call, then ``sample_fn(logits [B,V], state, emit_pos [B], active
    [B]) -> (tokens [B] int32, state)`` (the scheduler passes
    models/sampling.sample_step_batched, the shared sample+penalty-ring
    implementation) — so the emitted stream is bit-identical to K
    sequential plain ticks: same logits, same key splits, same ring
    updates (pinned by tests/test_fused_decode.py).

    **EOS parks inside the scan**: a row whose sampled token is in
    ``stop_ids`` ([n] int32; () disables) retires mid-fusion — its
    length stops advancing, its ring writes drop, and its next-token
    feed freezes, exactly the state the host-side release would have
    produced between two plain ticks. Later positions of a retired row
    are garbage the caller discards (the host stops consuming a row's
    burst at its stop token). The caller guarantees every active row can
    absorb ``num_steps`` tokens of KV budget (the scheduler's adaptive-K
    guard); EOS is the only mid-scan retirement.

    Returns (tokens [num_steps, B] int32, emitted [num_steps, B] bool —
    whether the row was live when that step sampled, next_tokens [B,1],
    cache, active [B], sample_state).
    """
    if step_fn is None:
        step_fn = decode_step if pages is None else decode_step_paged
    B = tokens.shape[0]
    if active is None:
        active = jnp.ones((B,), bool)
    stop = jnp.asarray(stop_ids, jnp.int32).reshape(-1)

    def step(carry, _):
        tokens, cache, act, state = carry
        emit_pos = cache.lengths + 1       # emitted token's context slot
        if pages is None:
            logits, cache = step_fn(params, config, tokens, cache, mesh,
                                    rules, active=act, kv_window=kv_window)
        else:
            logits, cache = step_fn(params, config, tokens, cache, mesh,
                                    rules, active=act, pages=pages,
                                    interpret=interpret)
        toks, state = sample_fn(logits[:, 0, :], state, emit_pos, act)
        # Parked rows keep their previous input token (the plain
        # program's exact next-token rule).
        next_tokens = jnp.where(act[:, None], toks[:, None], tokens)
        emitted = act
        if stop.shape[0]:
            act = act & jnp.all(toks[:, None] != stop[None, :], axis=1)
        return (next_tokens, cache, act, state), (toks, emitted)

    (tokens, cache, active, sample_state), (toks_all, emitted) = \
        jax.lax.scan(step, (tokens, cache, active, sample_state), None,
                     length=num_steps)
    return toks_all, emitted, tokens, cache, active, sample_state


def verify_step(params: dict, config: ModelConfig, tokens: jax.Array,
                cache: KVCache, mesh: Optional[Mesh] = None,
                rules: LogicalRules = DEFAULT_RULES,
                kv_window: Optional[int] = None,
                mlp_fn=None,
                last_idx: Optional[jax.Array] = None
                ) -> tuple[jax.Array, KVCache]:
    """Speculative-decoding verify: score S candidate positions per row in
    ONE forward (the multi-token generalisation of :func:`decode_step`).

    tokens: [B,S] = [current token, draft_0, ..., draft_{S-2}] per row;
    row b's position j writes cache slot ``lengths[b]+j`` and attends
    slots [0, lengths[b]+j]. Lengths are NOT advanced here — the caller
    runs its acceptance rule (models/sampling.spec_verify_batched) on the
    returned logits and advances by ``accepted+1``. Slots past the
    accepted prefix hold rejected drafts' kv: stale beyond the new
    length, overwritten before anything trusts them (the same invariant
    that parks rows — speculative rollback is free). The caller caps
    acceptance for near-budget rows; their untrusted writes past
    ``max_seq`` drop (see _block).

    Returns (logits [B,S,vocab] f32 — logits[:, j] is the model's
    distribution for the token AFTER input j — and the cache with the S
    candidate slots written, lengths unchanged). ``last_idx`` ([B] int):
    gather ONE position's logits per row ([B,1,vocab]) — the
    session-wake admission shape, where S is a whole suffix bucket and
    the full [B,S,vocab] f32 logits would be gigabytes (see
    forward's last_idx note); spec verify reads all S and passes None.
    """
    B, S = tokens.shape
    positions = cache.lengths[:, None] + jnp.arange(S)[None, :]   # [B,S]
    window = kv_window if kv_window is not None else cache.k.shape[2]
    # Query j of row b may see kv slots [0, lengths[b]+j] (its own slot
    # included — matches decode_step's lengths+1 masking at S=1).
    mask = (jnp.arange(window)[None, None, :]
            <= positions[:, :, None])[:, None]                    # [B,1,S,W]
    return forward(params, config, tokens, positions, cache, mask,
                   mesh, rules, kv_window=kv_window, mlp_fn=mlp_fn,
                   last_idx=last_idx)


def tree_attention_mask(lengths: jax.Array, anc: jax.Array,
                        window: int) -> jax.Array:
    """Tree-topology attention mask for :func:`verify_tree`.

    lengths: [B] committed context lengths; anc: [B,N,N] bool — anc[b,i,j]
    iff tree node j is on node i's root path (self included). Node i
    occupies cache slot ``lengths[b]+i``, so its query may see every
    committed slot (< lengths[b]) plus exactly the node slots on its own
    ancestor path — siblings and other branches stay invisible, which is
    what makes one batched forward score every root path as if each were
    verified alone. Returns [B,1,N,W] (True = attend).
    """
    B, N = anc.shape[:2]
    cols = jnp.arange(window)[None, :]                       # [1,W]
    committed = cols < lengths[:, None]                      # [B,W]
    jr = cols - lengths[:, None]                             # [B,W]
    node_col = (jr >= 0) & (jr < N)
    anc_w = jnp.take_along_axis(anc, jnp.clip(jr, 0, N - 1)[:, None, :],
                                axis=2)                      # [B,N,W]
    mask = committed[:, None, :] | (node_col[:, None, :] & anc_w)
    return mask[:, None]                                     # [B,1,N,W]


def verify_tree(params: dict, config: ModelConfig, tokens: jax.Array,
                depths: jax.Array, anc: jax.Array, cache: KVCache,
                mesh: Optional[Mesh] = None,
                rules: LogicalRules = DEFAULT_RULES,
                kv_window: Optional[int] = None,
                mlp_fn=None) -> tuple[jax.Array, KVCache]:
    """Tree-speculation verify: score N tree nodes per row in ONE forward
    (:func:`verify_step` generalised from a chain to a tree).

    tokens: [B,N] — node 0 is the root (current token), nodes 1..K the
    main draft chain, the rest sibling leaves; depths: [B,N] node depth
    (root = 0); anc: [B,N,N] ancestor matrix (see
    :func:`tree_attention_mask`). Node i writes cache slot ``lengths+i``
    (slots stay node-indexed, so rejected branches are stale-beyond-
    length exactly like rejected linear drafts) while its RoPE position
    is ``lengths+depths[i]`` — the position in the hypothetical stream
    its root path spells out. Lengths are NOT advanced; the caller runs
    models/sampling.spec_verify_tree on the logits, compacts a used
    sibling's kv onto the accepted path, and advances by accepted+1.

    Returns (logits [B,N,vocab] f32 — logits[:, i] is the distribution
    AFTER node i along its root path — and the cache with the N node
    slots written, lengths unchanged).
    """
    B, N = tokens.shape
    positions = cache.lengths[:, None] + depths              # RoPE [B,N]
    write_pos = cache.lengths[:, None] + jnp.arange(N)[None, :]
    window = kv_window if kv_window is not None else cache.k.shape[2]
    mask = tree_attention_mask(cache.lengths, anc, window)
    return forward(params, config, tokens, positions, cache, mask,
                   mesh, rules, kv_window=kv_window, mlp_fn=mlp_fn,
                   write_pos=write_pos)


# -- paged decode (Pallas kernel path) ----------------------------------------

def _constrain_pool(cache, mesh: Optional[Mesh],
                    rules: LogicalRules):
    """Pin the paged pool's kv-head sharding inside the jitted step so
    TP serving never silently replicates it (ops/paged_kv.shard_cache
    places it at creation; this keeps XLA from resharding mid-program)."""
    if mesh is None:
        return cache
    out = cache._replace(
        k=constrain(cache.k, mesh, (None, None, None, "kv_heads", None),
                    rules),
        v=constrain(cache.v, mesh, (None, None, None, "kv_heads", None),
                    rules))
    if cache.k_scale is not None:
        out = out._replace(
            k_scale=constrain(cache.k_scale, mesh,
                              (None, None, "kv_heads", None), rules),
            v_scale=constrain(cache.v_scale, mesh,
                              (None, None, "kv_heads", None), rules))
    return out


def verify_step_paged(params: dict, config: ModelConfig, tokens: jax.Array,
                      cache, mesh: Optional[Mesh] = None,
                      rules: LogicalRules = DEFAULT_RULES,
                      *, pages: int, interpret: Optional[bool] = None,
                      mlp_fn=None, last_idx: Optional[jax.Array] = None):
    """Speculative verify over the paged pool: :func:`verify_step`'s
    contract (S candidate positions, lengths unchanged; caller advances
    by accepted+1) on a PagedKVCache.

    Structure mirrors decode_step_paged's default path: position j
    attends the pool window plus block positions i <= j from the
    in-register k/v (ops/paged_attention.paged_attention_verify_append —
    one softmax over the concatenated scores, identical results to the
    write-then-attend ordering), the scan stacks each layer's block k/v,
    and ONE batched scatter lands everything afterwards
    (write_decode_multi_all_layers — positions past a row's allocation
    land in garbage page 0, so rollback/containment is inherent). The
    weight stream, the quantity speculation amortises, is still read
    once. ``pages`` must cover ``lengths`` on the gather path and
    ``lengths + S`` on the non-gather impls, which keep the per-layer
    write-then-attend ordering and read the drafts back from the pool
    (the scheduler sizes for ``kv_window + S``, covering both).

    Unlike the decode tick, verify stays on the gather path at EVERY
    window: the flash-append kernel is single-position (its online-
    softmax state is seeded with one current token), and the verify
    forward runs only when the scheduler's acceptance EMA says drafts
    are landing — a multi-position flash verify is recorded headroom,
    not a gap (docs/serving.md round-8).
    """
    from ..ops import paged_attention
    from ..ops.paged_attention import (_DEFAULT_IMPL,
                                       paged_attention_verify_append)
    from ..ops.paged_kv import (write_decode_multi,
                                write_decode_multi_all_layers)

    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    cache = _constrain_pool(cache, mesh, rules)
    B, S = tokens.shape
    positions = cache.lengths[:, None] + jnp.arange(S)[None, :]    # [B,S]
    h = params["embed"][tokens]
    h = constrain(h, mesh, ("batch", None, "act_embed"), rules)
    inv_freq = rope_frequencies(config)

    def finish(h):
        h = rms_norm(h, params["final_norm"], config.rms_norm_eps)
        if last_idx is not None:
            # One position's logits per row ([B,1,vocab]) — the
            # session-wake admission shape, where S is a whole suffix
            # bucket and full logits would be an [B*S, vocab] f32 temp
            # (forward's last_idx note). Spec verify passes None.
            h = jnp.take_along_axis(
                h, last_idx[:, None, None].astype(jnp.int32), axis=1)
        lm_head = (params["embed"].T if config.tie_embeddings
                   else params["lm_head"])
        logits = mm(h, lm_head).astype(jnp.float32)
        return constrain(logits, mesh, ("batch", None, "act_vocab"), rules)

    if _DEFAULT_IMPL == "gather":
        def body(h, layer):
            lp = _layer_view(params["layers"], layer)
            q, k, v = _attn_qkv(h, lp, config, inv_freq, positions, mesh,
                                rules)
            attn = paged_attention_verify_append(
                q, k, v, cache, cache.lengths, layer, pages=pages)
            h = _post_attn(h, attn, lp, config, mesh, rules, mlp_fn)
            return h, (k, v)

        h, (k_all, v_all) = jax.lax.scan(
            body, h, jnp.arange(config.num_layers))
        cache = write_decode_multi_all_layers(cache, k_all, v_all)
        return finish(h), cache

    def body(carry, layer):
        h, pk, pv, sk, sv = carry
        lp = _layer_view(params["layers"], layer)
        q, k, v = _attn_qkv(h, lp, config, inv_freq, positions, mesh, rules)
        step_cache = cache._replace(k=pk, v=pv, k_scale=sk, v_scale=sv)
        step_cache = write_decode_multi(step_cache, layer, k, v)
        outs = []
        for j in range(S):         # static unroll — S = spec_k+1, small
            outs.append(paged_attention(
                q[:, j], step_cache.k, step_cache.v, cache.page_table,
                cache.lengths + j + 1, layer, pages=pages,
                interpret=interpret, k_scale=step_cache.k_scale,
                v_scale=step_cache.v_scale))
        attn = jnp.stack(outs, axis=1)                             # [B,S,H,D]
        h = _post_attn(h, attn, lp, config, mesh, rules, mlp_fn)
        return (h, step_cache.k, step_cache.v, step_cache.k_scale,
                step_cache.v_scale), None

    (h, new_k, new_v, new_sk, new_sv), _ = jax.lax.scan(
        body, (h, cache.k, cache.v, cache.k_scale, cache.v_scale),
        jnp.arange(config.num_layers))
    return finish(h), cache._replace(k=new_k, v=new_v, k_scale=new_sk,
                                     v_scale=new_sv)


def verify_tree_paged(params: dict, config: ModelConfig, tokens: jax.Array,
                      depths: jax.Array, anc: jax.Array, cache,
                      mesh: Optional[Mesh] = None,
                      rules: LogicalRules = DEFAULT_RULES,
                      *, pages: int, mlp_fn=None):
    """:func:`verify_tree` on a PagedKVCache.

    Always rides verify_step_paged's gather path (regardless of the
    decode impl): every node's query attends the committed pool window
    (ops/paged_attention._gather_window_scores — ``pos < lengths`` is
    already branch-agnostic) plus the in-register block k/v filtered by
    the ancestor matrix ``anc`` instead of the chain-causal triangle.
    RoPE positions are ``lengths+depths``; ONE batched scatter lands
    node i at pool position ``lengths+i`` afterwards
    (write_decode_multi_all_layers — node-indexed slots, beyond-
    allocation writes land in garbage page 0, so rejected-branch
    containment is inherent, int8 scales included).
    """
    from ..ops.paged_attention import paged_attention_verify_append
    from ..ops.paged_kv import write_decode_multi_all_layers

    cache = _constrain_pool(cache, mesh, rules)
    positions = cache.lengths[:, None] + depths              # RoPE [B,N]
    h = params["embed"][tokens]
    h = constrain(h, mesh, ("batch", None, "act_embed"), rules)
    inv_freq = rope_frequencies(config)

    def body(h, layer):
        lp = _layer_view(params["layers"], layer)
        q, k, v = _attn_qkv(h, lp, config, inv_freq, positions, mesh,
                            rules)
        attn = paged_attention_verify_append(
            q, k, v, cache, cache.lengths, layer, pages=pages,
            block_mask=anc)
        h = _post_attn(h, attn, lp, config, mesh, rules, mlp_fn)
        return h, (k, v)

    h, (k_all, v_all) = jax.lax.scan(body, h, jnp.arange(config.num_layers))
    cache = write_decode_multi_all_layers(cache, k_all, v_all)
    h = rms_norm(h, params["final_norm"], config.rms_norm_eps)
    lm_head = (params["embed"].T if config.tie_embeddings
               else params["lm_head"])
    logits = mm(h, lm_head).astype(jnp.float32)
    return constrain(logits, mesh, ("batch", None, "act_vocab"),
                     rules), cache


def decode_step_paged(params: dict, config: ModelConfig, tokens: jax.Array,
                      cache, mesh: Optional[Mesh] = None,
                      rules: LogicalRules = DEFAULT_RULES,
                      active: Optional[jax.Array] = None,
                      *, pages: int, interpret: Optional[bool] = None,
                      mlp_fn=None):
    """One autoregressive step over the paged KV pool (ops/paged_kv.py).

    Same contract as :func:`decode_step` — including the parked-row
    invariant, which paging strengthens: a released row's zeroed page
    table routes its garbage writes to the shared garbage page, so parked
    rows cannot touch any live page. Attention runs the Pallas
    flash-decode kernel (ops/paged_attention.py) walking ``pages`` table
    entries per row (the serving window ladder:
    ``pages = ceil(window / page_size)``).

    cache: ops.paged_kv.PagedKVCache. Returns (logits [B,1,vocab], cache
    with lengths advanced where active).

    Structure note: the default (gather-impl) path attends BEFORE the
    pool write — the current token's k/v folds into attention via one
    exact online-softmax merge (ops/paged_attention.
    paged_attention_append) — and the scan stacks each layer's k/v so
    ONE batched scatter lands the whole step afterwards
    (write_decode_all_layers). Per-layer pool scatters inside the scan
    carry a fixed cost that was measurable against the decode bandwidth
    bound. Non-gather attention impls keep the write-then-attend
    ordering (their kernels read the pool for every position).

    Impl selection is delegated per layer call: paged_attention_append
    itself promotes to the multi-chunk flash-append kernel at windows
    >= PAGED_APPEND_FLASH_MIN_W (2048) on TPU — the round-8 long-window
    default — and the decision is made ONCE per trace (the scan body
    traces once), so the serving scheduler's per-window jitted programs
    each bake in exactly one impl and warmup compiles the whole
    gather/kernel ladder up front (serve/scheduler.warmup).
    """
    from ..ops import paged_attention
    from ..ops.paged_kv import PagedKVCache, write_decode, write_decode_burst
    from ..ops.paged_attention import _DEFAULT_IMPL, paged_attention_append

    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    cache = _constrain_pool(cache, mesh, rules)
    B = tokens.shape[0]
    positions = cache.lengths[:, None]                 # [B,1]
    h = params["embed"][tokens]
    h = constrain(h, mesh, ("batch", None, "act_embed"), rules)
    inv_freq = rope_frequencies(config)
    inc = (jnp.ones_like(cache.lengths) if active is None
           else active.astype(jnp.int32))

    def finish(h):
        h = rms_norm(h, params["final_norm"], config.rms_norm_eps)
        lm_head = (params["embed"].T if config.tie_embeddings
                   else params["lm_head"])
        logits = mm(h, lm_head).astype(jnp.float32)
        return constrain(logits, mesh, ("batch", None, "act_vocab"), rules)

    if _DEFAULT_IMPL == "gather":
        def body(h, layer):
            lp = _layer_view(params["layers"], layer)
            q, k, v = _attn_qkv(h, lp, config, inv_freq, positions, mesh,
                                rules)
            attn = paged_attention_append(q[:, 0], k[:, 0], v[:, 0], cache,
                                          cache.lengths, layer, pages=pages,
                                          interpret=interpret)
            h = _post_attn(h, attn[:, None], lp, config, mesh, rules,
                           mlp_fn)
            return h, (k[:, 0], v[:, 0])

        h, (k_all, v_all) = jax.lax.scan(
            body, h, jnp.arange(config.num_layers))
        return finish(h), write_decode_burst(cache, k_all, v_all, inc)

    def body(carry, layer):
        h, pk, pv, sk, sv = carry
        lp = _layer_view(params["layers"], layer)
        q, k, v = _attn_qkv(h, lp, config, inv_freq, positions, mesh, rules)
        step_cache = cache._replace(k=pk, v=pv, k_scale=sk, v_scale=sv)
        step_cache = write_decode(step_cache, layer, k[:, 0], v[:, 0])
        attn = paged_attention(q[:, 0], step_cache.k, step_cache.v,
                               cache.page_table, cache.lengths + 1, layer,
                               pages=pages, interpret=interpret,
                               k_scale=step_cache.k_scale,
                               v_scale=step_cache.v_scale)
        h = _post_attn(h, attn[:, None], lp, config, mesh, rules, mlp_fn)
        return (h, step_cache.k, step_cache.v, step_cache.k_scale,
                step_cache.v_scale), None

    (h, new_k, new_v, new_sk, new_sv), _ = jax.lax.scan(
        body, (h, cache.k, cache.v, cache.k_scale, cache.v_scale),
        jnp.arange(config.num_layers))
    return finish(h), cache._replace(k=new_k, v=new_v, k_scale=new_sk,
                                     v_scale=new_sv,
                                     lengths=cache.lengths + inc)
