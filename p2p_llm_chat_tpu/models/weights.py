"""Checkpoint loading: HF-format safetensors -> our stacked param trees.

The reference pulls model weights out-of-tree via ``ollama pull``
(README.md:62-70); the in-tree equivalent reads HuggingFace-layout
checkpoints (config.json + *.safetensors) from local disk and materialises
them directly into (optionally sharded) ``jax.Array``s.

Key transforms vs the HF torch layout:
- torch ``nn.Linear`` stores ``[out, in]`` and computes ``x @ W.T``; we
  store ``[in, out]`` — so every projection is transposed on load.
- per-layer tensors are stacked along a leading ``num_layers`` axis to
  match the lax.scan decoder (models/llama.py).
- with a mesh, each stacked tensor is device_put with its logical-axis
  sharding, so a 70B checkpoint never needs to fit on one chip
  (BASELINE.json config 4).
"""

from __future__ import annotations

import functools
import json
import os
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding

from ..utils.log import get_logger
from ..parallel.sharding import LogicalRules, DEFAULT_RULES, spec_for
from .configs import CONFIGS, ModelConfig, RopeScaling

log = get_logger("weights")


# -- HF name mapping ----------------------------------------------------------

def _dense_layer_map(i: int) -> dict[str, tuple[str, bool]]:
    """our layer key -> (HF tensor name, transpose?)."""
    p = f"model.layers.{i}"
    return {
        "attn_norm": (f"{p}.input_layernorm.weight", False),
        "wq": (f"{p}.self_attn.q_proj.weight", True),
        "wk": (f"{p}.self_attn.k_proj.weight", True),
        "wv": (f"{p}.self_attn.v_proj.weight", True),
        "wo": (f"{p}.self_attn.o_proj.weight", True),
        "mlp_norm": (f"{p}.post_attention_layernorm.weight", False),
        "w_gate": (f"{p}.mlp.gate_proj.weight", True),
        "w_up": (f"{p}.mlp.up_proj.weight", True),
        "w_down": (f"{p}.mlp.down_proj.weight", True),
    }


def _moe_layer_map(i: int, num_experts: int) -> dict[str, Any]:
    """Mixtral layout: experts w1 (gate), w3 (up), w2 (down) + router gate."""
    p = f"model.layers.{i}"
    m: dict[str, Any] = {
        "attn_norm": (f"{p}.input_layernorm.weight", False),
        "wq": (f"{p}.self_attn.q_proj.weight", True),
        "wk": (f"{p}.self_attn.k_proj.weight", True),
        "wv": (f"{p}.self_attn.v_proj.weight", True),
        "wo": (f"{p}.self_attn.o_proj.weight", True),
        "mlp_norm": (f"{p}.post_attention_layernorm.weight", False),
        "router": (f"{p}.block_sparse_moe.gate.weight", True),
        "w_gate": [(f"{p}.block_sparse_moe.experts.{e}.w1.weight", True)
                   for e in range(num_experts)],
        "w_up": [(f"{p}.block_sparse_moe.experts.{e}.w3.weight", True)
                 for e in range(num_experts)],
        "w_down": [(f"{p}.block_sparse_moe.experts.{e}.w2.weight", True)
                   for e in range(num_experts)],
    }
    return m


def convert_hf_state_dict(state: dict[str, np.ndarray], config: ModelConfig,
                          dtype=jnp.bfloat16) -> dict:
    """Convert a flat HF state dict (numpy arrays) into our stacked tree.
    Test-oracle path (used by the parity tests); load_checkpoint below is
    the production path over safetensors files."""
    def get(name: str, transpose: bool) -> np.ndarray:
        t = state[name]
        return np.ascontiguousarray(t.T) if transpose else t

    L = config.num_layers
    layers: dict[str, Any] = {}
    maps = [( _moe_layer_map(i, config.num_experts) if config.is_moe
              else _dense_layer_map(i)) for i in range(L)]
    for key in maps[0]:
        per_layer = []
        for i in range(L):
            spec = maps[i][key]
            if isinstance(spec, list):   # per-expert stack
                per_layer.append(np.stack([get(n, t) for n, t in spec]))
            else:
                per_layer.append(get(*spec))
        layers[key] = jnp.asarray(np.stack(per_layer), dtype)

    params: dict[str, Any] = {
        "embed": jnp.asarray(state["model.embed_tokens.weight"], dtype),
        "layers": layers,
        "final_norm": jnp.asarray(state["model.norm.weight"], dtype),
    }
    if not config.tie_embeddings:
        params["lm_head"] = jnp.asarray(
            np.ascontiguousarray(state["lm_head.weight"].T), dtype)
    return params


# -- safetensors checkpoint directory loading --------------------------------

def config_from_hf_json(path: str) -> ModelConfig:
    """Derive a ModelConfig from an HF config.json (llama/mixtral families)."""
    with open(path) as f:
        hf = json.load(f)
    arch = (hf.get("architectures") or ["LlamaForCausalLM"])[0]
    rope_scaling = None
    rs = hf.get("rope_scaling")
    if rs and rs.get("rope_type", rs.get("type")) == "llama3":
        rope_scaling = RopeScaling(
            factor=float(rs.get("factor", 8.0)),
            low_freq_factor=float(rs.get("low_freq_factor", 1.0)),
            high_freq_factor=float(rs.get("high_freq_factor", 4.0)),
            original_max_position=int(rs.get("original_max_position_embeddings", 8192)),
        )
    num_heads = int(hf["num_attention_heads"])
    eos = hf.get("eos_token_id", 2)
    eos_ids = tuple(eos) if isinstance(eos, list) else (int(eos),)
    return ModelConfig(
        name=hf.get("_name_or_path", "hf-model"),
        vocab_size=int(hf["vocab_size"]),
        hidden_size=int(hf["hidden_size"]),
        intermediate_size=int(hf["intermediate_size"]),
        num_layers=int(hf["num_hidden_layers"]),
        num_heads=num_heads,
        num_kv_heads=int(hf.get("num_key_value_heads", num_heads)),
        # Mixtral configs carry an explicit ``"head_dim": null``.
        head_dim=int(hf.get("head_dim") or hf["hidden_size"] // num_heads),
        max_seq_len=int(hf.get("max_position_embeddings", 8192)),
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        rope_scaling=rope_scaling,
        rms_norm_eps=float(hf.get("rms_norm_eps", 1e-5)),
        tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
        num_experts=int(hf.get("num_local_experts", 0)),
        num_experts_per_tok=int(hf.get("num_experts_per_tok", 0)),
        bos_token_id=int(hf.get("bos_token_id", 1)),
        eos_token_ids=eos_ids,
    )


def _reverse_name_map(config: ModelConfig) -> dict[str, tuple]:
    """HF tensor name -> (leaf key path, layer index or None, expert index
    or None, transpose?) for every per-layer tensor, plus the top-level
    names. Derived from the same forward maps the batch loader uses, so
    the two loaders cannot drift."""
    out: dict[str, tuple] = {
        "model.embed_tokens.weight": (("embed",), None, None, False),
        "model.norm.weight": (("final_norm",), None, None, False),
    }
    if not config.tie_embeddings:
        out["lm_head.weight"] = (("lm_head",), None, None, True)
    for i in range(config.num_layers):
        m = (_moe_layer_map(i, config.num_experts) if config.is_moe
             else _dense_layer_map(i))
        for key, spec in m.items():
            if isinstance(spec, list):
                for e, (name, tr) in enumerate(spec):
                    out[name] = (("layers", key), i, e, tr)
            else:
                name, tr = spec
                out[name] = (("layers", key), i, None, tr)
    return out


def _iter_hf_tensors(ckpt_dir: str, config: ModelConfig):
    """Yield ``(leaf_path, layer, expert, np_tensor)`` for every mapped
    tensor across the dir's safetensors shards, transpose already applied
    (host RAM holds one tensor at a time). Shared by the streaming and
    streamed-int8 loaders so the shard walk / name map / missing-tensor
    accounting cannot drift between them. Raises FileNotFoundError with
    no shards; KeyError when mapped tensors are absent (zeros where
    weights should be = garbage logits with no error — fail loudly)."""
    from safetensors import safe_open

    name_map = _reverse_name_map(config)
    missing = set(name_map)
    shards = sorted(f for f in os.listdir(ckpt_dir)
                    if f.endswith(".safetensors"))
    if not shards:
        raise FileNotFoundError(f"no .safetensors files in {ckpt_dir}")
    for shard in shards:
        with safe_open(os.path.join(ckpt_dir, shard),
                       framework="numpy") as f:
            for name in f.keys():
                entry = name_map.get(name)
                if entry is None:
                    continue
                path, layer, expert, transpose = entry
                t = f.get_tensor(name)
                if transpose:
                    t = np.ascontiguousarray(t.T)
                missing.discard(name)
                yield path, layer, expert, t
        log.info("streamed shard %s (%d/%d tensors placed)", shard,
                 len(name_map) - len(missing), len(name_map))
    if missing:
        raise KeyError(
            f"checkpoint {ckpt_dir} is missing {len(missing)} expected "
            f"tensor(s), e.g. {sorted(missing)[:3]} — truncated download "
            "or wrong config?")


def load_checkpoint_streaming(ckpt_dir: str,
                              config: Optional[ModelConfig] = None,
                              mesh: Optional[Mesh] = None,
                              rules: LogicalRules = DEFAULT_RULES,
                              dtype=jnp.bfloat16,
                              ) -> tuple[dict, ModelConfig]:
    """Memory-bounded checkpoint load: host RAM holds ONE tensor at a
    time; the stacked tree lives on device (sharded when a mesh is given)
    from the start.

    The batch loader (:func:`load_checkpoint`) materialises the whole HF
    state dict in host numpy before stacking — ~140 GB for llama3.1-70B
    bf16, the memory-fit hard part SURVEY.md §7 names. Here every leaf is
    pre-allocated on device (zeros, with its logical sharding) and each
    safetensors tensor is spliced into its (layer[, expert]) slice via a
    donated ``dynamic_update_index_in_dim`` — one compiled splice program
    per leaf shape, reused across layers, so host peak stays at the
    largest single tensor and device memory at the final tree size.
    """
    from . import family_for

    if config is None:
        config = config_from_hf_json(os.path.join(ckpt_dir, "config.json"))
    family = family_for(config)
    axes = family.param_axes(config)

    def sharding(path_axes):
        if mesh is None:
            return None
        return NamedSharding(mesh, spec_for(path_axes, rules))

    abstract = jax.eval_shape(
        lambda: family.init_params(config, jax.random.PRNGKey(0),
                                   dtype=dtype))
    params = jax.tree.map(
        lambda a, ax: jnp.zeros(a.shape, a.dtype, device=sharding(ax)),
        abstract, axes,
        is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct))

    # One donated splice program per (leaf shape, index arity).
    @functools.partial(jax.jit, donate_argnums=(0,), static_argnums=(3,))
    def splice(full, t, idx, two_level):
        if two_level:
            return jax.lax.dynamic_update_slice(
                full, t[None, None], (idx[0], idx[1]) + (0,) * t.ndim)
        return jax.lax.dynamic_update_index_in_dim(full, t, idx[0], 0)

    def get_leaf(path):
        node = params
        for p in path:
            node = node[p]
        return node

    def set_leaf(path, value):
        node = params
        for p in path[:-1]:
            node = node[p]
        node[path[-1]] = value

    for path, layer, expert, t in _iter_hf_tensors(ckpt_dir, config):
        leaf = get_leaf(path)
        if layer is None:
            set_leaf(path, jax.device_put(
                jnp.asarray(t, dtype),
                leaf.sharding if mesh is not None else None))
        else:
            idx = (jnp.asarray(layer, jnp.int32),
                   jnp.asarray(0 if expert is None else expert,
                               jnp.int32))
            set_leaf(path, splice(leaf, jnp.asarray(t, dtype),
                                  idx, expert is not None))
    log.info("loaded %s (streaming): %.2fB params", config.name,
             sum(x.size for x in jax.tree.leaves(params)) / 1e9)
    return params, config


def load_checkpoint(ckpt_dir: str, config: Optional[ModelConfig] = None,
                    mesh: Optional[Mesh] = None,
                    rules: LogicalRules = DEFAULT_RULES,
                    dtype=jnp.bfloat16,
                    param_axes_fn: Optional[Callable[[ModelConfig], dict]] = None,
                    ) -> tuple[dict, ModelConfig]:
    """Load an HF-layout checkpoint directory into a (sharded) param tree.

    Reads every ``*.safetensors`` shard, converts/stacks, and — when a mesh
    is given — places each tensor with its logical sharding so per-host
    memory stays bounded by the shard size, not the model size.
    """
    from safetensors import safe_open

    if config is None:
        config = config_from_hf_json(os.path.join(ckpt_dir, "config.json"))

    state: dict[str, np.ndarray] = {}
    shards = sorted(f for f in os.listdir(ckpt_dir) if f.endswith(".safetensors"))
    if not shards:
        raise FileNotFoundError(f"no .safetensors files in {ckpt_dir}")
    for shard in shards:
        with safe_open(os.path.join(ckpt_dir, shard), framework="numpy") as f:
            for name in f.keys():
                state[name] = f.get_tensor(name)
        log.info("read shard %s (%d tensors total)", shard, len(state))

    params = convert_hf_state_dict(state, config, dtype)
    if mesh is not None:
        if param_axes_fn is None:
            from . import family_for
            param_axes_fn = family_for(config).param_axes
        axes = param_axes_fn(config)
        params = jax.tree.map(
            lambda x, a: jax.device_put(x, NamedSharding(mesh, spec_for(a, rules))),
            params, axes,
            is_leaf=lambda x: isinstance(x, jax.Array),
        )
    log.info("loaded %s: %.2fB params", config.name,
             sum(x.size for x in jax.tree.leaves(params)) / 1e9)
    return params, config


class UnsupportedForQuantizedLoad(ValueError):
    """The checkpoint's family is outside load_checkpoint_quantized's
    scope — callers fall back to the standard paths. A dedicated type so
    fallbacks cannot swallow REAL load errors (corrupt shards etc.),
    which must propagate."""


# Fields that determine whether a caller-supplied config names the SAME
# MODEL as a checkpoint: every tensor-shape-bearing field (plus the
# registry name, native checkpoints only — see _check_config_identity).
# Deliberately excluded: max_seq_len, rope_theta/rope_scaling, eps,
# token-id defaults, moe_capacity_factor — serving/runtime knobs that
# registry bumps legitimately change without re-saving weights (e.g. the
# bench-1b max_seq_len 2048 -> 16384 bump for long-context rows, which
# the old whole-dataclass equality would have rejected for every
# pre-existing native checkpoint).
_CONFIG_IDENTITY_FIELDS = (
    "vocab_size", "hidden_size", "intermediate_size", "num_layers",
    "num_heads", "num_kv_heads", "head_dim", "tie_embeddings",
    "num_experts", "num_experts_per_tok",
)


def _check_config_identity(supplied: ModelConfig, stored: ModelConfig,
                           ckpt_dir: str, check_name: bool = True) -> None:
    """Raise unless ``supplied`` names the same model as the checkpoint's
    own ``stored`` config — identity-relevant fields only (see
    _CONFIG_IDENTITY_FIELDS). On agreement the SUPPLIED config wins:
    honoring its benign (non-shape) field bumps is the point.

    ``check_name``: native checkpoints carry the registry name they were
    saved under, so name disagreement means a different model; HF dirs
    derive ``name`` from config.json's ``_name_or_path`` (or the literal
    "hf-model"), which can NEVER equal a registry name — the HF branch
    passes False and lets the shape fields alone establish identity."""
    fields = _CONFIG_IDENTITY_FIELDS + (("name",) if check_name else ())
    bad = [f for f in fields if getattr(supplied, f) != getattr(stored, f)]
    if bad:
        raise ValueError(
            f"config mismatch: caller passed {supplied.name!r} but the "
            f"checkpoint at {ckpt_dir} carries {stored.name!r} "
            f"(differing identity fields: {', '.join(bad)})")


def load_checkpoint_quantized(ckpt_dir: str,
                              config: Optional[ModelConfig] = None,
                              quant: str = "int8",
                              ) -> tuple[dict, ModelConfig]:
    """Single-chip big-model load: stream a checkpoint (HF safetensors or
    native Orbax) straight into the FUSED quantized stacked tree — the
    bf16 device tree never exists. ``quant``: ``int8`` (per-channel) or
    ``int4`` (group-wise packed nibbles — half the int8 stream again;
    ~3.8 GB for the 8B trunk).

    Why: ``load_checkpoint`` + ``quantize_params`` peaks at the full bf16
    model on the chip (~16 GB for llama3.1-8B — does not fit a 16 GB
    v5e), even though the int8 model (~8.6 GB) plus an int8 KV pool does.
    This is the checkpoint-path twin of ``llama.init_params_quantized``
    (which solved the same problem for random init): per layer, the host
    tensors are quantized host-side and spliced into donated stacked int8
    buffers in ``fuse_params``' wqkv/wgu layout — quantize-then-fuse
    equivalence holds exactly (per-output-channel scales concatenate with
    their columns).

    Weights round through bf16 (the serving compute dtype) before
    quantization, so the result is BIT-IDENTICAL to load-at-bf16 ->
    quantize_params -> fuse_params (pinned by tests for both formats
    and both precisions — the host numpy quantizers below mirror
    quant.quantize / quant.quantize4's exact IEEE f32 ops).
    For f32-SAVED native checkpoints the old single-chip path would have
    quantized unrounded f32 — that path cannot fit big models anyway, and
    all in-tree saves default to bf16.

    MoE (mixtral-family) checkpoints stream the same way: attention
    fuses to wqkv exactly like dense, and the per-expert ffn leaves
    quantize into the fused ``wgu_e`` [L,NE,H,2F] + ``w_down``
    [L,NE,F,H] stacks (mixtral.moe_mlp's single-einsum layout); the
    router stays bf16 (tiny, f32 routing math). Unknown families raise
    :class:`UnsupportedForQuantizedLoad`. Tied-embedding configs return
    no ``lm_head`` leaf (forward uses ``embed.T``, kept bf16).
    """
    from . import family_for, llama, mixtral
    from .checkpoint import is_native_checkpoint, peek_config
    from .checkpoint import load_checkpoint as load_native
    from .quant import QTensor, QTensor4, stream_bufs

    if quant not in ("int8", "int4"):
        raise ValueError(f"quant must be int8|int4, got {quant!r}")
    dtype = jnp.bfloat16

    # Family gate FIRST — from metadata alone. Checking after the tensor
    # reads would load a rejected multi-GB checkpoint in full, only for
    # the engine to re-load it through the standard path.
    native = is_native_checkpoint(ckpt_dir)
    if config is None:
        config = (peek_config(ckpt_dir) if native else
                  config_from_hf_json(os.path.join(ckpt_dir, "config.json")))
    else:
        # A caller-supplied config must name the same MODEL as the
        # checkpoint — identity fields only, so benign registry bumps
        # (max_seq_len, rope knobs) survive pre-existing checkpoints.
        # Applied to BOTH branches: the HF path used to skip the check
        # entirely (silently trusting the caller), the native one used
        # whole-dataclass equality (rejecting every benign bump).
        stored = (peek_config(ckpt_dir) if native else
                  config_from_hf_json(os.path.join(ckpt_dir,
                                                   "config.json")))
        _check_config_identity(config, stored, ckpt_dir, check_name=native)
    family = family_for(config)
    if family not in (llama, mixtral):
        raise UnsupportedForQuantizedLoad(
            "load_checkpoint_quantized covers the llama and mixtral "
            f"families; {config.name} keeps the standard load paths")
    moe = config.is_moe
    layer_keys = (("attn_norm", "wq", "wk", "wv", "wo", "mlp_norm",
                   "router", "w_gate", "w_up", "w_down") if moe else
                  ("attn_norm", "wq", "wk", "wv", "wo",
                   "mlp_norm", "w_gate", "w_up", "w_down"))

    # -- per-layer host-tensor iterator -------------------------------------
    if native:
        cpu = jax.devices("cpu")[0]
        host_params, loaded_cfg = load_native(ckpt_dir, device=cpu)
        # Identity agreement with the caller's config was checked above
        # (relaxed to _CONFIG_IDENTITY_FIELDS — ADVICE r4's consistency
        # point, minus the whole-dataclass equality that rejected benign
        # runtime-field bumps); re-verify against the ACTUALLY-loaded
        # config in case peek and load ever disagree. The supplied
        # config stays authoritative for non-identity fields.
        _check_config_identity(config, loaded_cfg, ckpt_dir)

        def layer_host(li: int) -> dict[str, np.ndarray]:
            lp = host_params["layers"]
            return {k: np.asarray(lp[k][li]) for k in layer_keys}

        def top_host() -> dict[str, np.ndarray]:
            out = {"embed": np.asarray(host_params["embed"]),
                   "final_norm": np.asarray(host_params["final_norm"])}
            if "lm_head" in host_params:
                out["lm_head"] = np.asarray(host_params["lm_head"])
            return out
    else:
        host_params = None

        def _read_all() -> tuple[dict, dict]:
            """One pass over the shards (shared iterator), grouped per
            layer. Host peak is the full tree for HF dirs read this way —
            acceptable (host RAM >> HBM); the DEVICE peak is what this
            loader bounds. Per-expert tensors stack into [NE, ...] host
            arrays in expert order."""
            per_layer: dict[int, dict] = {}
            top: dict[str, np.ndarray] = {}
            for path, layer, expert, t in _iter_hf_tensors(ckpt_dir,
                                                           config):
                if layer is None:
                    top[path[-1]] = t
                elif expert is None:
                    per_layer.setdefault(layer, {})[path[-1]] = t
                else:
                    per_layer.setdefault(layer, {}).setdefault(
                        path[-1], {})[expert] = t
            for lt in per_layer.values():
                for k, v in lt.items():
                    if isinstance(v, dict):
                        lt[k] = np.stack([v[e] for e in range(len(v))])
            return per_layer, top

        _layers_np, _top_np = _read_all()

        def layer_host(li: int) -> dict[str, np.ndarray]:
            return _layers_np[li]

        def top_host() -> dict[str, np.ndarray]:
            return _top_np

    # -- per-layer host quantize + donated device splice --------------------
    # Quantization happens in HOST numpy, mirroring quant.quantize's exact
    # IEEE f32 ops (abs-max / 127 per output column, round-half-even) —
    # in-jit quantization may fuse the divide/round and drift +-1 from the
    # eager quantize_params path, breaking the bit-identity contract.
    L, H = config.num_layers, config.hidden_size
    E, NE = config.intermediate_size, config.num_experts
    if moe:
        dims: dict[str, tuple] = {
            "wqkv": (H, config.q_dim + 2 * config.kv_dim),
            "wo": (config.q_dim, H),
            "wgu_e": (NE, H, 2 * E),
            "w_down": (NE, E, H),
        }
    else:
        dims = {
            "wqkv": (H, config.q_dim + 2 * config.kv_dim),
            "wo": (config.q_dim, H),
            "wgu": (H, 2 * E),
            "w_down": (E, H),
        }
    bufs = {name: stream_bufs(L, shape, quant)
            for name, shape in dims.items()}

    import ml_dtypes

    def _bf16_round(w: np.ndarray) -> np.ndarray:
        # Round through bf16 first: the reference path (load bf16 tree,
        # then quantize_params) sees bf16-rounded weights, and HF shards
        # are often f32 — skipping the rounding would drift the scales.
        return np.asarray(w).astype(ml_dtypes.bfloat16).astype(np.float32)

    def _host_quant8(w: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        # axis=-2 is the contraction axis for 2-D projections and the
        # [NE, H, F] expert stacks alike (quant.quantize's axis).
        wf = _bf16_round(w)
        amax = np.abs(wf).max(axis=-2, keepdims=True)
        s = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
        q = np.clip(np.round(wf / s), -127, 127).astype(np.int8)
        return q, s

    def _host_quant4(w: np.ndarray, group: int) -> tuple[np.ndarray,
                                                         np.ndarray]:
        # quant.quantize4's exact math in host numpy: group-wise abs-max
        # / 7, round-half-even, clip to [-7, 7], split-half nibble pack
        # (quant.pack4's layout; the uint8 view IS the explicit wrap).
        wf = _bf16_round(w)
        K = wf.shape[-2]
        ng = K // group
        g = wf.reshape(*wf.shape[:-2], ng, group, wf.shape[-1])
        amax = np.abs(g).max(axis=-2, keepdims=True)
        s = np.where(amax > 0, amax / 7.0, 1.0).astype(np.float32)
        qv = np.clip(np.round(g / s), -7, 7).astype(np.int32)
        qv = qv.reshape(*wf.shape[:-2], K, wf.shape[-1])
        lo = qv[..., :K // 2, :] + 8
        hi = qv[..., K // 2:, :] + 8
        q = (lo | (hi << 4)).astype(np.uint8).view(np.int8)
        return q, np.squeeze(s, -2)

    def host_quant(w: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        # Per-leaf precision mirrors quant._quantize_leaf via the SAME
        # group chooser (per-layer leaves: dense 2-D, expert stacks
        # 3-D — matching _quantize_leaf's streaming-loop default).
        from .quant import _int4_group
        group = (_int4_group(w.shape[-2], w.ndim >= 3)
                 if quant == "int4" else None)
        if group is not None:
            return _host_quant4(w, group)
        return _host_quant8(w)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def splice_layer(bufs, qs, layer):
        out = dict(bufs)
        for name, (q, s) in qs.items():
            out[name] = type(bufs[name])(q=bufs[name].q.at[layer].set(q),
                                         s=bufs[name].s.at[layer].set(s))
        return out

    attn_norms = np.zeros((L, H), np.float32)
    mlp_norms = np.zeros((L, H), np.float32)
    routers = np.zeros((L, H, NE), np.float32) if moe else None
    for li in range(L):
        lt = layer_host(li)
        attn_norms[li] = lt["attn_norm"].astype(np.float32)
        mlp_norms[li] = lt["mlp_norm"].astype(np.float32)
        fused = {
            "wqkv": np.concatenate(
                [lt["wq"], lt["wk"], lt["wv"]], axis=1),
            "wo": lt["wo"],
        }
        if moe:
            routers[li] = lt["router"].astype(np.float32)
            # Per-expert gate|up columns concatenate on the out axis —
            # scales concatenate with them (fused-quantize equivalence).
            fused["wgu_e"] = np.concatenate(
                [lt["w_gate"], lt["w_up"]], axis=-1)
            fused["w_down"] = lt["w_down"]
        else:
            fused["wgu"] = np.concatenate(
                [lt["w_gate"], lt["w_up"]], axis=1)
            fused["w_down"] = lt["w_down"]
        qs = {}
        for name, w in fused.items():
            q, s = host_quant(w)
            qs[name] = (jnp.asarray(q), jnp.asarray(s))
        bufs = splice_layer(bufs, qs, jnp.asarray(li))

    top = top_host()
    layers: dict = {
        "attn_norm": jnp.asarray(attn_norms, dtype),
        "mlp_norm": jnp.asarray(mlp_norms, dtype),
        **bufs,
    }
    if moe:
        layers["router"] = jnp.asarray(routers, dtype)
    params: dict = {
        "embed": jnp.asarray(top["embed"], dtype),
        "layers": layers,
        "final_norm": jnp.asarray(top["final_norm"], dtype),
    }
    if not config.tie_embeddings:
        # Host-side too: a device quantize of the 8B lm_head would spike
        # ~3 GB of bf16-upload + f32 temp on a chip already holding the
        # quantized tree (the same spike removed from synth.py's quote
        # head). The class mirrors host_quant's per-leaf precision
        # choice (quant._quantize_leaf's predicate).
        head = top["lm_head"]
        from .quant import _int4_group
        cls = (QTensor4 if (quant == "int4"
                            and _int4_group(head.shape[-2], False))
               else QTensor)
        q, s = host_quant(head)
        params["lm_head"] = cls(q=jnp.asarray(q), s=jnp.asarray(s))
    jax.block_until_ready(params)
    del host_params
    from .quant import quant_mode
    mode = quant_mode(params) or "int8"
    n_logical = sum(
        (2 * x.q.size if isinstance(x, QTensor4) else x.size)
        for x in jax.tree.leaves(
            params, is_leaf=lambda v: isinstance(v, QTensor4)))
    log.info("loaded %s quantized+fused (streaming, single-chip): "
             "%.2fB params %s", config.name, n_logical / 1e9, mode)
    return params, config
