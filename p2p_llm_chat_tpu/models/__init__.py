"""JAX model definitions for the TPU serving stack.

The reference delegates all modelling to Ollama (SURVEY.md §1 L4); these are
the in-tree replacements mandated by BASELINE.json's configs: the llama
family (3.1-8B / 3.1-70B and smaller test sizes) and Mixtral-8x7B MoE.

Design (TPU-first, not a port of any torch code):

- pure-functional: params are nested dicts of ``jax.Array``; forward passes
  are plain jitted functions. No framework Module state.
- layers are *stacked* along a leading ``num_layers`` axis and the decoder
  runs as one ``lax.scan`` — O(1) XLA graph size in depth, fast compiles
  for 32-80 layer models.
- every parameter/activation has a logical-axis annotation
  (parallel/sharding.py) so the same code runs single-chip, tensor-parallel
  or expert-parallel by switching the mesh.
- compute in bfloat16 on the MXU, reductions/norms in float32.
"""

from .configs import ModelConfig, CONFIGS, get_config
from . import llama


def family_for(config: ModelConfig):
    """The model module (llama or mixtral) implementing this config.

    Both families expose the same functional surface — init_params,
    param_axes, prefill, decode_step (identical signatures and KVCache
    contract) — so the serving stack (serve/scheduler.py, serve/engine.py)
    and the driver dryrun dispatch on ``config.is_moe`` alone.
    """
    if config.is_moe:
        from . import mixtral
        return mixtral
    return llama


__all__ = ["ModelConfig", "CONFIGS", "get_config", "llama", "family_for"]
