"""Pallas w8a16 + w4a16 matmuls: quantized weights dequantized in VMEM.

Why this kernel exists: XLA on TPU does not stream int8 dot operands —
``x @ q.astype(bf16)`` (and the mixed-dtype ``dot_general``) materialise
a full bf16 copy of the weight in HBM before the matmul, so "int8"
decode read MORE bytes than bf16 (measured on a v5e chip: 22-layer
decode trunk 4.0 ms with the convert vs 2.9 ms plain bf16 — the int8
read + bf16 write + bf16 read round trip). Here each program DMAs an
int8 ``[block_h, block_o]`` weight tile straight into VMEM, converts it
there (VPU, free next to the HBM stream), and feeds the MXU — HBM sees
int8 only, which is the entire point of weight-only quantization for
bandwidth-bound decode (models/quant.py).

Grid ``(O/block_o, H/block_h)`` with the contraction (H) innermost: the
f32 accumulator tile stays resident in VMEM scratch across the H walk
and is scaled (per-output-channel ``s``) once on the last step.

Used by models/quant.mm for small-row calls (decode/verify ticks — the
bandwidth-bound shapes); prefill keeps the XLA path, where the convert
cost is amortised over thousands of rows and the matmul is
compute-bound. ``interpret=True`` runs on CPU for hardware-free parity
tests (tests/test_quant.py).

The w4a16 kernels (:func:`quant_matmul4` / :func:`quant_matmul_stacked4`)
stream the PACKED int4 bytes — HBM weight traffic is half of int8's,
the entire point — and unpack nibbles + fold group-wise scales in VMEM.
They run the 1D whole-contraction grid only, statically unrolled over
SEGMENTS of the split-half packing (models/quant.pack4): each segment of
packed byte rows unpacks one small [seg, bo] tile (a whole-stripe int32
unpack would blow VMEM at 8B dims), runs two [rows, seg] x [seg, bo]
dots, and scales each after its dot — the segment width is chosen so
every dot's logical rows fall inside ONE scale group, which is what
makes scale-after-dot legal per group. Even group counts walk whole
groups (seg = G: packed rows ``[g*G, (g+1)*G)`` are exactly logical
group ``g`` low-nibble and group ``ng/2 + g`` high-nibble); odd group
counts walk HALF-groups (seg = G/2: the hi-nibble half starts at
logical row ng*G/2 — a half-group boundary — so whole-group segments
would straddle two scales, half-group segments never do).
Preconditions (:func:`int4_stripe_seg`): group % 128 == 0 for even
counts, group % 256 == 0 for odd ones (x slices must stay lane-
aligned at the segment width); everything else takes the dequant XLA
fallback in models/quant.mm.

The ``*_experts_stacked`` kernels extend both precisions to the 4-D
MoE expert pools [L, NE, H, O]: grid (NE, O/bo) per layer, each program
DMAing one expert's whole-contraction stripe, so the top-k gathered
expert matmuls of models/mixtral.moe_mlp ride the quantized stream
instead of falling back to an XLA dequant of the full expert stack.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Weight-tile candidates, first divisor wins. All lane-aligned (x128) and
# int8-sublane-aligned (x32). Bigger tiles = fewer program invocations
# (the per-program cost is what erodes the bandwidth win at decode);
# 1024x1024 int8 = 1 MiB of VMEM per tile, comfortably resident.
_BLOCK_CANDIDATES = (1024, 512, 256, 128)

# VMEM budget for ONE whole-contraction weight stripe [H, bo] int8 on the
# 1D-grid path (~16 MB VMEM/core; Mosaic double-buffers the stripe, so the
# working set is 2x this, leaving room for x/out/everything else). Chosen
# so bench-1b's w_down (H=5632) still runs whole-H stripes at bo=512.
# QMM_STRIPE_BUDGET overrides (bytes; 0 forces the 2D grid everywhere).
import os as _os

_STRIPE_BUDGET_BYTES = int(_os.environ.get("QMM_STRIPE_BUDGET",
                                           4 * 1024 * 1024))

# Ceiling for the fully-resident x block of the 1D whole-contraction
# grid (x [rows, H] bf16 + two double-buffered weight stripes must fit
# ~16 MB VMEM). Calls above it use the 2D grid, whose x blocks tile over
# H — hit by 512-row prefill-admission chunks at 8B dims (rows x 14336
# bf16 = 14.7 MB, observed as a compile-time VMEM OOM).
_X_VMEM_BUDGET_BYTES = 6 * 1024 * 1024

# Per-hidden-size output-tile autotune table for the 1D whole-stripe
# grids, SHARED by w8a16 and w4a16 (both route block choice through
# _pick_1d_bo, so identical logical shapes pick identical grids in both
# precisions). Key = logical contraction dim, value = bo cap. Why it
# exists: the stripe machinery was tuned at hidden=2048 (bench-1b),
# where bo=1024 keeps >= 6 programs in flight per matmul; at hidden=1024
# (draft-400m) the same bo leaves a 2048-col projection only TWO grid
# programs — too shallow for Mosaic to overlap the next stripe's DMA
# with the current dot, recorded as the stacked kernel losing ~5% to
# forced XLA (ROADMAP round-8 MoE note). Capping bo at 256 restores
# >= 8 programs and the double-buffer overlap; tests/test_quant.py pins
# the dispatch decision, tools/check_quant_kernel.py measures it on
# chip. Caps only apply when they divide O (else the next smaller
# candidate divisor wins via the normal search).
#
# MoE expert contractions (round-18): 2816 is bench-moe's w_down stripe
# — uncapped it picks bo=1024 and leaves the O=1024 projection ONE grid
# program (no DMA/compute overlap at all, the hidden=1024 failure mode
# taken to its limit); 128 restores 8 programs. 11520 is mixtral-large's
# w_down: the 4 MiB stripe budget already shrinks it to bo=256, pinned
# here so the decision survives budget retunes (grid depth 16 at
# O=4096). Both derive from the same grid-depth arithmetic the
# hidden=1024 probe measured; tools/check_quant_kernel.py carries the
# expert-shape matrix for the on-chip confirmation (BASELINE.md
# round-18 deferral).
_TILE_TABLE = {1024: 256, 2816: 128, 11520: 256}


def _qmm_kernel(x_ref, q_ref, s_ref, o_ref, acc_ref):
    j = pl.program_id(1)
    num_h = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[...]                                 # [rows, bh] bf16
    q = q_ref[...].astype(x.dtype)                 # int8 -> bf16 in VMEM
    acc_ref[:] += jax.lax.dot(x, q, preferred_element_type=jnp.float32)

    @pl.when(j == num_h - 1)
    def _finalise():
        s = s_ref[0].astype(jnp.float32)           # [bo]
        o_ref[...] = (acc_ref[:] * s[None, :]).astype(o_ref.dtype)


def _qmm_kernel_1d(x_ref, q_ref, s_ref, o_ref):
    """Whole-contraction stripe: one program = one [H, bo] weight tile =
    one output tile — no revisits, no scratch accumulator, and ~3x fewer
    program invocations than the 2D grid at decode shapes (measured: the
    per-program fixed cost, not DMA bandwidth, dominated the 2D walk)."""
    x = x_ref[...]                                 # [rows, H] bf16
    q = q_ref[...].astype(x.dtype)                 # int8 -> bf16 in VMEM
    acc = jax.lax.dot(x, q, preferred_element_type=jnp.float32)
    s = s_ref[0].astype(jnp.float32)               # [bo]
    o_ref[...] = (acc * s[None, :]).astype(o_ref.dtype)


def _qmm_kernel_1d_stacked(layer_ref, x_ref, q_ref, s_ref, o_ref):
    """Whole-contraction stripe fetched from the STACKED [L, H, O] weight
    at the scalar-prefetched layer index. This is how the decode scan
    avoids materialising per-layer weight slices: a pallas custom-call
    cannot alias a dynamic-slice view, so feeding it sliced operands made
    XLA copy every layer's int8 weights before the matmul — measured at
    ~1.9 ms of a 3.8 ms bench-1b step (half the step!). With the stacked
    operand the kernel DMAs tiles straight from the scan-invariant pool."""
    x = x_ref[...]                                 # [rows, H] bf16
    q = q_ref[0].astype(x.dtype)                   # [H, bo] int8 -> bf16
    acc = jax.lax.dot(x, q, preferred_element_type=jnp.float32)
    s = s_ref[0, 0].astype(jnp.float32)            # [bo]
    o_ref[...] = (acc * s[None, :]).astype(o_ref.dtype)


def _qmm_kernel_2d_stacked(layer_ref, x_ref, q_ref, s_ref, o_ref, acc_ref):
    j = pl.program_id(1)
    num_h = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[...]                                 # [rows, bh]
    q = q_ref[0].astype(x.dtype)                   # [bh, bo]
    acc_ref[:] += jax.lax.dot(x, q, preferred_element_type=jnp.float32)

    @pl.when(j == num_h - 1)
    def _finalise():
        s = s_ref[0, 0].astype(jnp.float32)        # [bo]
        o_ref[...] = (acc_ref[:] * s[None, :]).astype(o_ref.dtype)


def _qmm4_body(x, pk_rows, s_rows, o_dtype):
    """Shared w4a16 kernel body: x [rp, K]; pk_rows [K/2, bo] packed
    int8; s_rows [ng, bo] f32. Statically unrolled over SEGMENTS of the
    split-half packing: each iteration unpacks ONE [seg, bo] tile to
    int32 (small — a whole-stripe unpack would blow VMEM at K=14336),
    runs two [rp, seg] x [seg, bo] dots and folds each group's scale
    after its dot (legal per group: the segment width divides the group
    so a dot's contraction never crosses a scale boundary — see
    :func:`int4_stripe_seg` for why odd counts need half-group
    segments). Nibble math stays in int32 where & 0xF and the
    arithmetic >> 4 are sign-robust for negative reinterpreted bytes."""
    K = x.shape[1]
    ng = s_rows.shape[0]
    G = K // ng
    seg = int4_stripe_seg(K, ng)
    acc = jnp.zeros((x.shape[0], pk_rows.shape[1]), jnp.float32)
    for t in range((K // 2) // seg):
        pk = pk_rows[t * seg:(t + 1) * seg, :].astype(jnp.int32)
        w_lo = ((pk & 0xF) - 8).astype(x.dtype)
        w_hi = (((pk >> 4) & 0xF) - 8).astype(x.dtype)
        # Logical rows of this segment: low nibbles at t*seg, high
        # nibbles at K/2 + t*seg; both offsets are seg-multiples and seg
        # divides G, so each lies inside exactly one scale group.
        s_lo = s_rows[(t * seg) // G, :].astype(jnp.float32)
        s_hi = s_rows[(K // 2 + t * seg) // G, :].astype(jnp.float32)
        acc += jax.lax.dot(x[:, t * seg:(t + 1) * seg], w_lo,
                           preferred_element_type=jnp.float32) * s_lo[None, :]
        acc += jax.lax.dot(x[:, K // 2 + t * seg:K // 2 + (t + 1) * seg],
                           w_hi,
                           preferred_element_type=jnp.float32) * s_hi[None, :]
    return acc.astype(o_dtype)


def _qmm4_kernel_1d(x_ref, q_ref, s_ref, o_ref):
    """w4a16 whole-contraction stripe: one program = one [K/2, bo] PACKED
    weight tile = one output tile. HBM reads the int4-packed bytes only."""
    o_ref[...] = _qmm4_body(x_ref[...], q_ref[...], s_ref[...], o_ref.dtype)


def _qmm4_kernel_1d_stacked(layer_ref, x_ref, q_ref, s_ref, o_ref):
    """w4a16 stacked twin: the [L, K/2, O] packed pool is read at the
    scalar-prefetched layer index, no per-layer slice materialisation —
    same motivation as _qmm_kernel_1d_stacked."""
    o_ref[...] = _qmm4_body(x_ref[...], q_ref[0], s_ref[0], o_ref.dtype)


def _qmm_kernel_experts_stacked(layer_ref, x_ref, q_ref, s_ref, o_ref):
    """w8a16 expert stripe: one program = one expert's whole-contraction
    [H, bo] tile from the [L, NE, H, O] pool at the scalar-prefetched
    layer — the batched-expert twin of _qmm_kernel_1d_stacked. The
    expert axis is the OUTER grid dim, so the per-expert x block
    [C, H] is fetched once and the O/bo stripe walk streams under it."""
    x = x_ref[0]                                   # [Cp, H] bf16
    q = q_ref[0, 0].astype(x.dtype)                # [H, bo] int8 -> bf16
    acc = jax.lax.dot(x, q, preferred_element_type=jnp.float32)
    s = s_ref[0, 0, 0].astype(jnp.float32)         # [bo]
    o_ref[0] = (acc * s[None, :]).astype(o_ref.dtype)


def _qmm4_kernel_experts_stacked(layer_ref, x_ref, q_ref, s_ref, o_ref):
    """w4a16 expert stripe over the [L, NE, K/2, O] packed pool — the
    batched-expert twin of _qmm4_kernel_1d_stacked, sharing the
    segment-walk body (and its odd-group support)."""
    o_ref[0] = _qmm4_body(x_ref[0], q_ref[0, 0], s_ref[0, 0], o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def quant_matmul_stacked(x: jax.Array, q: jax.Array, s: jax.Array,
                         layer: jax.Array, *,
                         interpret: bool = False) -> jax.Array:
    """``x @ dequant(q[layer], s[layer])`` reading the stacked weight
    directly — no per-layer slice copy (see _qmm_kernel_1d_stacked).

    x: [rows, H]; q: [L, H, O] int8; s: [L, 1, O] f32 (the stacked
    models/quant.QTensor layout); layer: scalar int32. Same block
    preconditions as :func:`quant_matmul`.
    """
    rows, H = x.shape
    O = q.shape[2]
    bh, bo = pick_block(H), pick_block(O)
    if bh is None or bo is None:
        raise ValueError(f"no block divides H={H} / O={O}; use the XLA path")
    pad = (-rows) % 8
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    rp = rows + pad
    ly = jnp.asarray(layer, jnp.int32).reshape(1)

    bo_1d = _pick_1d_bo(rp, H, O, x.dtype.itemsize)
    if bo_1d is not None:
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(O // bo_1d,),
            in_specs=[
                pl.BlockSpec((rp, H), lambda i, ly: (0, 0)),
                pl.BlockSpec((1, H, bo_1d), lambda i, ly: (ly[0], 0, i)),
                pl.BlockSpec((1, 1, bo_1d), lambda i, ly: (ly[0], 0, i)),
            ],
            out_specs=pl.BlockSpec((rp, bo_1d), lambda i, ly: (0, i)),
        )
        out = pl.pallas_call(
            _qmm_kernel_1d_stacked,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((rp, O), x.dtype),
            interpret=interpret,
        )(ly, x, q, s)
        return out[:rows] if pad else out

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(O // bo, H // bh),
        in_specs=[
            pl.BlockSpec((rp, bh), lambda i, j, ly: (0, j)),
            pl.BlockSpec((1, bh, bo), lambda i, j, ly: (ly[0], j, i)),
            pl.BlockSpec((1, 1, bo), lambda i, j, ly: (ly[0], 0, i)),
        ],
        out_specs=pl.BlockSpec((rp, bo), lambda i, j, ly: (0, i)),
        scratch_shapes=[pltpu.VMEM((rp, bo), jnp.float32)],
    )
    out = pl.pallas_call(
        _qmm_kernel_2d_stacked,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((rp, O), x.dtype),
        interpret=interpret,
    )(ly, x, q, s)
    return out[:rows] if pad else out


def pick_block(dim: int) -> int | None:
    for b in _BLOCK_CANDIDATES:
        if dim % b == 0:
            return b
    return None


def _pick_1d_bo(rp: int, H: int, O: int, x_itemsize: int,
                stripe_rows: int | None = None) -> int | None:
    """Output-block width for the 1D whole-contraction grid, or None to
    use the 2D grid: x [rp, H] must fit the VMEM x-budget and the
    [stripe_rows, bo] weight stripe the stripe budget (stripe_rows
    defaults to H — int8's byte rows; the int4 path passes H/2, its
    PACKED byte rows). The per-hidden-size _TILE_TABLE caps bo below the
    budget-driven choice where measurement says shallower grids lose to
    XLA. Shared by the stacked and unstacked kernels of both precisions
    so identical shapes always pick identical grids."""
    if rp * H * x_itemsize > _X_VMEM_BUDGET_BYTES:
        return None
    sr = H if stripe_rows is None else stripe_rows
    bo = pick_block(O)
    cap = _TILE_TABLE.get(H)
    if cap is not None and bo is not None and bo > cap and O % cap == 0:
        bo = cap
    while bo is not None and sr * bo > _STRIPE_BUDGET_BYTES:
        bo = next((b for b in _BLOCK_CANDIDATES
                   if b < bo and O % b == 0), None)
    return bo


def int4_stripe_seg(K: int, ng: int) -> int | None:
    """Segment width (in packed byte rows) of the w4a16 stripe walk for
    contraction ``K`` with ``ng`` scale groups, or None if the kernels
    cannot serve the grouping — the single coverage gate every int4
    dispatch decision derives from (the expert-stripe table of the
    round-18 MoE work; pick_int4_bo and _qmm4_body both consult it).

    Even counts walk whole groups: seg = G, needing G % 128 == 0 for
    lane-aligned x slices. Odd counts CANNOT walk whole groups — the
    hi-nibble half starts at logical row ng*G/2, a half-group boundary,
    so a whole-group segment would straddle two scales — they walk
    half-groups instead: seg = G/2, needing G % 256 == 0 to keep the
    half-width slices lane-aligned. G=64 shapes (and odd counts at
    G=128) fall back to the XLA dequant path in models/quant.
    """
    if ng <= 0 or K % ng or K % 2:
        return None
    G = K // ng
    if ng % 2 == 0:
        return G if G % 128 == 0 else None
    return G // 2 if G % 256 == 0 else None


def pick_expert_bo(rows: int, H: int, O: int,
                   x_itemsize: int) -> int | None:
    """Output-block width for the w8a16 expert-stripe kernel, or None ->
    models/quant.q_einsum keeps the XLA dequant path. The same budget /
    tile-table search as the dense 1D grids, applied to ONE expert's
    [C, H] bucket and [H, bo] stripe (there is no 2D fallback for the
    expert grid — uncovered shapes are prefill-class and XLA's batched
    einsum is the right tool there anyway)."""
    rp = rows + ((-rows) % 8)
    return _pick_1d_bo(rp, H, O, x_itemsize)


def pick_int4_bo(rows: int, H: int, O: int, ng: int,
                 x_itemsize: int) -> int | None:
    """Output-block width for the w4a16 1D whole-stripe kernel, or None
    -> models/quant.mm takes the dequant XLA fallback. The coverage
    gate is :func:`int4_stripe_seg` (even groups at G % 128 == 0, odd
    at G % 256 == 0 — the round-18 fix: the old even-only gate rejected
    odd expert group counts the segment walk now serves); the block
    width then comes from the shared budget/tile-table search.
    """
    if int4_stripe_seg(H, ng) is None:
        return None
    rp = rows + ((-rows) % 8)
    return _pick_1d_bo(rp, H, O, x_itemsize, stripe_rows=H // 2)


@functools.partial(jax.jit, static_argnames=("interpret",))
def quant_matmul(x: jax.Array, q: jax.Array, s: jax.Array,
                 *, interpret: bool = False) -> jax.Array:
    """``(x @ dequant(q, s))`` with int8-only HBM weight traffic.

    x: [rows, H] (rows padded to a multiple of 8 here if needed);
    q: [H, O] int8; s: [1, O] f32 per-output-channel scales (the
    models/quant.QTensor layout). Returns [rows, O] in x.dtype.
    Caller guarantees H and O are divisible by a block candidate
    (models/quant.mm falls back to the XLA path otherwise).
    """
    rows, H = x.shape
    O = q.shape[1]
    bh, bo = pick_block(H), pick_block(O)
    if bh is None or bo is None:
        raise ValueError(f"no block divides H={H} / O={O}; use the XLA path")
    pad = (-rows) % 8
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    rp = rows + pad

    # Prefer the 1D whole-contraction grid: shrink bo until the [H, bo]
    # int8 stripe fits the VMEM budget (keeping bo a divisor of O).
    bo_1d = _pick_1d_bo(rp, H, O, x.dtype.itemsize)
    if bo_1d is not None:
        out = pl.pallas_call(
            _qmm_kernel_1d,
            grid=(O // bo_1d,),
            in_specs=[
                pl.BlockSpec((rp, H), lambda i: (0, 0)),
                pl.BlockSpec((H, bo_1d), lambda i: (0, i)),
                pl.BlockSpec((1, bo_1d), lambda i: (0, i)),
            ],
            out_specs=pl.BlockSpec((rp, bo_1d), lambda i: (0, i)),
            out_shape=jax.ShapeDtypeStruct((rp, O), x.dtype),
            interpret=interpret,
        )(x, q, s)
        return out[:rows] if pad else out

    out = pl.pallas_call(
        _qmm_kernel,
        grid=(O // bo, H // bh),
        in_specs=[
            pl.BlockSpec((rp, bh), lambda i, j: (0, j)),
            pl.BlockSpec((bh, bo), lambda i, j: (j, i)),
            pl.BlockSpec((1, bo), lambda i, j: (0, i)),
        ],
        out_specs=pl.BlockSpec((rp, bo), lambda i, j: (0, i)),
        scratch_shapes=[pltpu.VMEM((rp, bo), jnp.float32)],
        out_shape=jax.ShapeDtypeStruct((rp, O), x.dtype),
        interpret=interpret,
    )(x, q, s)
    return out[:rows] if pad else out


@functools.partial(jax.jit, static_argnames=("interpret",))
def quant_matmul4(x: jax.Array, q: jax.Array, s: jax.Array,
                  *, interpret: bool = False) -> jax.Array:
    """``x @ dequant4(q, s)`` with int4-PACKED HBM weight traffic.

    x: [rows, H]; q: [H/2, O] int8 packed nibbles (models/quant.pack4's
    split-half layout); s: [ng, O] f32 group scales. Returns [rows, O]
    in x.dtype. Caller guarantees :func:`pick_int4_bo` accepts the shape
    (models/quant.mm falls back to the dequant XLA path otherwise).
    """
    rows, H = x.shape
    O = q.shape[1]
    ng = s.shape[0]
    bo = pick_int4_bo(rows, H, O, ng, x.dtype.itemsize)
    if bo is None:
        raise ValueError(
            f"w4a16 kernel does not cover H={H} O={O} ng={ng}; use the "
            "XLA fallback (models/quant.mm gates on pick_int4_bo)")
    pad = (-rows) % 8
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    rp = rows + pad
    out = pl.pallas_call(
        _qmm4_kernel_1d,
        grid=(O // bo,),
        in_specs=[
            pl.BlockSpec((rp, H), lambda i: (0, 0)),
            pl.BlockSpec((H // 2, bo), lambda i: (0, i)),
            pl.BlockSpec((ng, bo), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((rp, bo), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((rp, O), x.dtype),
        interpret=interpret,
    )(x, q, s)
    return out[:rows] if pad else out


@functools.partial(jax.jit, static_argnames=("interpret",))
def quant_matmul_stacked4(x: jax.Array, q: jax.Array, s: jax.Array,
                          layer: jax.Array, *,
                          interpret: bool = False) -> jax.Array:
    """``x @ dequant4(q[layer], s[layer])`` reading the stacked packed
    pool directly — the int4 twin of :func:`quant_matmul_stacked`.

    x: [rows, H]; q: [L, H/2, O] int8 packed nibbles; s: [L, ng, O] f32
    group scales (the stacked models/quant.QTensor4 layout); layer:
    scalar int32. Same coverage contract as :func:`quant_matmul4`.
    """
    rows, H = x.shape
    O = q.shape[2]
    ng = s.shape[1]
    bo = pick_int4_bo(rows, H, O, ng, x.dtype.itemsize)
    if bo is None:
        raise ValueError(
            f"w4a16 kernel does not cover H={H} O={O} ng={ng}; use the "
            "XLA fallback (models/quant.mm gates on pick_int4_bo)")
    pad = (-rows) % 8
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    rp = rows + pad
    ly = jnp.asarray(layer, jnp.int32).reshape(1)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(O // bo,),
        in_specs=[
            pl.BlockSpec((rp, H), lambda i, ly: (0, 0)),
            pl.BlockSpec((1, H // 2, bo), lambda i, ly: (ly[0], 0, i)),
            pl.BlockSpec((1, ng, bo), lambda i, ly: (ly[0], 0, i)),
        ],
        out_specs=pl.BlockSpec((rp, bo), lambda i, ly: (0, i)),
    )
    out = pl.pallas_call(
        _qmm4_kernel_1d_stacked,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((rp, O), x.dtype),
        interpret=interpret,
    )(ly, x, q, s)
    return out[:rows] if pad else out


@functools.partial(jax.jit, static_argnames=("interpret",))
def quant_matmul_experts_stacked(x: jax.Array, q: jax.Array, s: jax.Array,
                                 layer: jax.Array, *,
                                 interpret: bool = False) -> jax.Array:
    """Batched per-expert ``x[e] @ dequant(q[layer, e], s[layer, e])``
    reading the 4-D expert pool directly — the MoE twin of
    :func:`quant_matmul_stacked`, for mixtral's capacity-bucket expert
    matmuls (models/quant.q_einsum dispatches here for decode-shaped
    buckets so the expert trunk streams int8 instead of an XLA dequant
    of the whole [NE, H, O] stack).

    x: [NE, C, H] expert buckets; q: [L, NE, H, O] int8;
    s: [L, NE, 1, O] f32; layer: scalar int32. Returns [NE, C, O].
    Caller guarantees ``pick_expert_bo`` accepts the shape.
    """
    NE, C, H = x.shape
    O = q.shape[-1]
    bo = pick_expert_bo(C, H, O, x.dtype.itemsize)
    if bo is None:
        raise ValueError(
            f"expert w8a16 kernel does not cover C={C} H={H} O={O}; use "
            "the XLA path (models/quant.q_einsum gates on pick_expert_bo)")
    pad = (-C) % 8
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    cp = C + pad
    ly = jnp.asarray(layer, jnp.int32).reshape(1)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(NE, O // bo),
        in_specs=[
            pl.BlockSpec((1, cp, H), lambda e, i, ly: (e, 0, 0)),
            pl.BlockSpec((1, 1, H, bo), lambda e, i, ly: (ly[0], e, 0, i)),
            pl.BlockSpec((1, 1, 1, bo), lambda e, i, ly: (ly[0], e, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, cp, bo), lambda e, i, ly: (e, 0, i)),
    )
    out = pl.pallas_call(
        _qmm_kernel_experts_stacked,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((NE, cp, O), x.dtype),
        interpret=interpret,
    )(ly, x, q, s)
    return out[:, :C] if pad else out


@functools.partial(jax.jit, static_argnames=("interpret",))
def quant_matmul_experts_stacked4(x: jax.Array, q: jax.Array, s: jax.Array,
                                  layer: jax.Array, *,
                                  interpret: bool = False) -> jax.Array:
    """int4 twin of :func:`quant_matmul_experts_stacked`: the packed
    [L, NE, H/2, O] expert pool streams at int4-packed bytes, unpacked
    per stripe by the shared segment walk (odd expert group counts
    included — mixtral-large's w_down groups at 256 into ng=45).

    x: [NE, C, H]; q: [L, NE, H/2, O] int8 packed nibbles;
    s: [L, NE, ng, O] f32 group scales; layer: scalar int32. Returns
    [NE, C, O]. Caller guarantees :func:`pick_int4_bo` accepts the
    per-expert shape.
    """
    NE, C, H = x.shape
    O = q.shape[-1]
    ng = s.shape[-2]
    bo = pick_int4_bo(C, H, O, ng, x.dtype.itemsize)
    if bo is None:
        raise ValueError(
            f"expert w4a16 kernel does not cover C={C} H={H} O={O} "
            f"ng={ng}; use the XLA fallback (models/quant.q_einsum gates "
            "on pick_int4_bo)")
    pad = (-C) % 8
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    cp = C + pad
    ly = jnp.asarray(layer, jnp.int32).reshape(1)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(NE, O // bo),
        in_specs=[
            pl.BlockSpec((1, cp, H), lambda e, i, ly: (e, 0, 0)),
            pl.BlockSpec((1, 1, H // 2, bo),
                         lambda e, i, ly: (ly[0], e, 0, i)),
            pl.BlockSpec((1, 1, ng, bo), lambda e, i, ly: (ly[0], e, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, cp, bo), lambda e, i, ly: (e, 0, i)),
    )
    out = pl.pallas_call(
        _qmm4_kernel_experts_stacked,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((NE, cp, O), x.dtype),
        interpret=interpret,
    )(ly, x, q, s)
    return out[:, :C] if pad else out
