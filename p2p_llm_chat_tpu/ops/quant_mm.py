"""Pallas w8a16 + w4a16 matmuls: quantized weights dequantized in VMEM.

Why this kernel exists: XLA on TPU does not stream int8 dot operands —
``x @ q.astype(bf16)`` (and the mixed-dtype ``dot_general``) materialise
a full bf16 copy of the weight in HBM before the matmul, so "int8"
decode read MORE bytes than bf16 (measured on a v5e chip: 22-layer
decode trunk 4.0 ms with the convert vs 2.9 ms plain bf16 — the int8
read + bf16 write + bf16 read round trip). Here each program DMAs an
int8 ``[block_h, block_o]`` weight tile straight into VMEM, converts it
there (VPU, free next to the HBM stream), and feeds the MXU — HBM sees
int8 only, which is the entire point of weight-only quantization for
bandwidth-bound decode (models/quant.py).

Grid ``(O/block_o, H/block_h)`` with the contraction (H) innermost: the
f32 accumulator tile stays resident in VMEM scratch across the H walk
and is scaled (per-output-channel ``s``) once on the last step.

Used by models/quant.mm for small-row calls (decode/verify ticks — the
bandwidth-bound shapes); prefill keeps the XLA path, where the convert
cost is amortised over thousands of rows and the matmul is
compute-bound. ``interpret=True`` runs on CPU for hardware-free parity
tests (tests/test_quant.py).

The w4a16 kernels (:func:`quant_matmul4` / :func:`quant_matmul_stacked4`)
stream the PACKED int4 bytes — HBM weight traffic is half of int8's,
the entire point — and unpack nibbles + fold group-wise scales in VMEM.
They run the 1D whole-contraction grid only, statically unrolled over
lo/hi group PAIRS of the split-half packing (models/quant.pack4): packed
byte rows ``[g*G, (g+1)*G)`` are exactly logical group ``g`` (low
nibbles) and group ``ng/2 + g`` (high nibbles), so each iteration
unpacks one small [G, bo] tile (a whole-stripe int32 unpack would blow
VMEM at 8B dims), runs two [rows, G] x [G, bo] dots, and scales each
after its dot — group scales are constant within a dot, which is what
makes scale-after-dot legal per group. Preconditions: even group count,
group % 128 == 0 (lane-aligned x slices); everything else takes the
dequant XLA fallback in models/quant.mm.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Weight-tile candidates, first divisor wins. All lane-aligned (x128) and
# int8-sublane-aligned (x32). Bigger tiles = fewer program invocations
# (the per-program cost is what erodes the bandwidth win at decode);
# 1024x1024 int8 = 1 MiB of VMEM per tile, comfortably resident.
_BLOCK_CANDIDATES = (1024, 512, 256, 128)

# VMEM budget for ONE whole-contraction weight stripe [H, bo] int8 on the
# 1D-grid path (~16 MB VMEM/core; Mosaic double-buffers the stripe, so the
# working set is 2x this, leaving room for x/out/everything else). Chosen
# so bench-1b's w_down (H=5632) still runs whole-H stripes at bo=512.
# QMM_STRIPE_BUDGET overrides (bytes; 0 forces the 2D grid everywhere).
import os as _os

_STRIPE_BUDGET_BYTES = int(_os.environ.get("QMM_STRIPE_BUDGET",
                                           4 * 1024 * 1024))

# Ceiling for the fully-resident x block of the 1D whole-contraction
# grid (x [rows, H] bf16 + two double-buffered weight stripes must fit
# ~16 MB VMEM). Calls above it use the 2D grid, whose x blocks tile over
# H — hit by 512-row prefill-admission chunks at 8B dims (rows x 14336
# bf16 = 14.7 MB, observed as a compile-time VMEM OOM).
_X_VMEM_BUDGET_BYTES = 6 * 1024 * 1024

# Per-hidden-size output-tile autotune table for the 1D whole-stripe
# grids, SHARED by w8a16 and w4a16 (both route block choice through
# _pick_1d_bo, so identical logical shapes pick identical grids in both
# precisions). Key = logical contraction dim, value = bo cap. Why it
# exists: the stripe machinery was tuned at hidden=2048 (bench-1b),
# where bo=1024 keeps >= 6 programs in flight per matmul; at hidden=1024
# (draft-400m) the same bo leaves a 2048-col projection only TWO grid
# programs — too shallow for Mosaic to overlap the next stripe's DMA
# with the current dot, recorded as the stacked kernel losing ~5% to
# forced XLA (ROADMAP round-8 MoE note). Capping bo at 256 restores
# >= 8 programs and the double-buffer overlap; tests/test_quant.py pins
# the dispatch decision, tools/check_quant_kernel.py measures it on
# chip. Caps only apply when they divide O (else the next smaller
# candidate divisor wins via the normal search).
_TILE_TABLE = {1024: 256}


def _qmm_kernel(x_ref, q_ref, s_ref, o_ref, acc_ref):
    j = pl.program_id(1)
    num_h = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[...]                                 # [rows, bh] bf16
    q = q_ref[...].astype(x.dtype)                 # int8 -> bf16 in VMEM
    acc_ref[:] += jax.lax.dot(x, q, preferred_element_type=jnp.float32)

    @pl.when(j == num_h - 1)
    def _finalise():
        s = s_ref[0].astype(jnp.float32)           # [bo]
        o_ref[...] = (acc_ref[:] * s[None, :]).astype(o_ref.dtype)


def _qmm_kernel_1d(x_ref, q_ref, s_ref, o_ref):
    """Whole-contraction stripe: one program = one [H, bo] weight tile =
    one output tile — no revisits, no scratch accumulator, and ~3x fewer
    program invocations than the 2D grid at decode shapes (measured: the
    per-program fixed cost, not DMA bandwidth, dominated the 2D walk)."""
    x = x_ref[...]                                 # [rows, H] bf16
    q = q_ref[...].astype(x.dtype)                 # int8 -> bf16 in VMEM
    acc = jax.lax.dot(x, q, preferred_element_type=jnp.float32)
    s = s_ref[0].astype(jnp.float32)               # [bo]
    o_ref[...] = (acc * s[None, :]).astype(o_ref.dtype)


def _qmm_kernel_1d_stacked(layer_ref, x_ref, q_ref, s_ref, o_ref):
    """Whole-contraction stripe fetched from the STACKED [L, H, O] weight
    at the scalar-prefetched layer index. This is how the decode scan
    avoids materialising per-layer weight slices: a pallas custom-call
    cannot alias a dynamic-slice view, so feeding it sliced operands made
    XLA copy every layer's int8 weights before the matmul — measured at
    ~1.9 ms of a 3.8 ms bench-1b step (half the step!). With the stacked
    operand the kernel DMAs tiles straight from the scan-invariant pool."""
    x = x_ref[...]                                 # [rows, H] bf16
    q = q_ref[0].astype(x.dtype)                   # [H, bo] int8 -> bf16
    acc = jax.lax.dot(x, q, preferred_element_type=jnp.float32)
    s = s_ref[0, 0].astype(jnp.float32)            # [bo]
    o_ref[...] = (acc * s[None, :]).astype(o_ref.dtype)


def _qmm_kernel_2d_stacked(layer_ref, x_ref, q_ref, s_ref, o_ref, acc_ref):
    j = pl.program_id(1)
    num_h = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[...]                                 # [rows, bh]
    q = q_ref[0].astype(x.dtype)                   # [bh, bo]
    acc_ref[:] += jax.lax.dot(x, q, preferred_element_type=jnp.float32)

    @pl.when(j == num_h - 1)
    def _finalise():
        s = s_ref[0, 0].astype(jnp.float32)        # [bo]
        o_ref[...] = (acc_ref[:] * s[None, :]).astype(o_ref.dtype)


def _qmm4_body(x, pk_rows, s_rows, o_dtype):
    """Shared w4a16 kernel body: x [rp, K]; pk_rows [K/2, bo] packed
    int8; s_rows [ng, bo] f32. Statically unrolled over the ng/2 group
    PAIRS of the split-half packing: packed byte rows [g*G, (g+1)*G) are
    logical group g in the low nibbles and group ng/2 + g in the high
    nibbles, so each iteration unpacks ONE [G, bo] tile to int32 (small —
    a whole-stripe unpack would blow VMEM at K=14336), runs two
    [rp, G] x [G, bo] dots and folds each group's scale after its dot
    (legal per group: the scale is constant within the dot's
    contraction). Nibble math stays in int32 where & 0xF and the
    arithmetic >> 4 are sign-robust for negative reinterpreted bytes."""
    K = x.shape[1]
    ng = s_rows.shape[0]
    G = K // ng
    half = ng // 2
    acc = jnp.zeros((x.shape[0], pk_rows.shape[1]), jnp.float32)
    for g in range(half):
        pk = pk_rows[g * G:(g + 1) * G, :].astype(jnp.int32)
        w_lo = ((pk & 0xF) - 8).astype(x.dtype)
        w_hi = (((pk >> 4) & 0xF) - 8).astype(x.dtype)
        s_lo = s_rows[g, :].astype(jnp.float32)
        s_hi = s_rows[half + g, :].astype(jnp.float32)
        acc += jax.lax.dot(x[:, g * G:(g + 1) * G], w_lo,
                           preferred_element_type=jnp.float32) * s_lo[None, :]
        acc += jax.lax.dot(x[:, K // 2 + g * G:K // 2 + (g + 1) * G], w_hi,
                           preferred_element_type=jnp.float32) * s_hi[None, :]
    return acc.astype(o_dtype)


def _qmm4_kernel_1d(x_ref, q_ref, s_ref, o_ref):
    """w4a16 whole-contraction stripe: one program = one [K/2, bo] PACKED
    weight tile = one output tile. HBM reads the int4-packed bytes only."""
    o_ref[...] = _qmm4_body(x_ref[...], q_ref[...], s_ref[...], o_ref.dtype)


def _qmm4_kernel_1d_stacked(layer_ref, x_ref, q_ref, s_ref, o_ref):
    """w4a16 stacked twin: the [L, K/2, O] packed pool is read at the
    scalar-prefetched layer index, no per-layer slice materialisation —
    same motivation as _qmm_kernel_1d_stacked."""
    o_ref[...] = _qmm4_body(x_ref[...], q_ref[0], s_ref[0], o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def quant_matmul_stacked(x: jax.Array, q: jax.Array, s: jax.Array,
                         layer: jax.Array, *,
                         interpret: bool = False) -> jax.Array:
    """``x @ dequant(q[layer], s[layer])`` reading the stacked weight
    directly — no per-layer slice copy (see _qmm_kernel_1d_stacked).

    x: [rows, H]; q: [L, H, O] int8; s: [L, 1, O] f32 (the stacked
    models/quant.QTensor layout); layer: scalar int32. Same block
    preconditions as :func:`quant_matmul`.
    """
    rows, H = x.shape
    O = q.shape[2]
    bh, bo = pick_block(H), pick_block(O)
    if bh is None or bo is None:
        raise ValueError(f"no block divides H={H} / O={O}; use the XLA path")
    pad = (-rows) % 8
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    rp = rows + pad
    ly = jnp.asarray(layer, jnp.int32).reshape(1)

    bo_1d = _pick_1d_bo(rp, H, O, x.dtype.itemsize)
    if bo_1d is not None:
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(O // bo_1d,),
            in_specs=[
                pl.BlockSpec((rp, H), lambda i, ly: (0, 0)),
                pl.BlockSpec((1, H, bo_1d), lambda i, ly: (ly[0], 0, i)),
                pl.BlockSpec((1, 1, bo_1d), lambda i, ly: (ly[0], 0, i)),
            ],
            out_specs=pl.BlockSpec((rp, bo_1d), lambda i, ly: (0, i)),
        )
        out = pl.pallas_call(
            _qmm_kernel_1d_stacked,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((rp, O), x.dtype),
            interpret=interpret,
        )(ly, x, q, s)
        return out[:rows] if pad else out

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(O // bo, H // bh),
        in_specs=[
            pl.BlockSpec((rp, bh), lambda i, j, ly: (0, j)),
            pl.BlockSpec((1, bh, bo), lambda i, j, ly: (ly[0], j, i)),
            pl.BlockSpec((1, 1, bo), lambda i, j, ly: (ly[0], 0, i)),
        ],
        out_specs=pl.BlockSpec((rp, bo), lambda i, j, ly: (0, i)),
        scratch_shapes=[pltpu.VMEM((rp, bo), jnp.float32)],
    )
    out = pl.pallas_call(
        _qmm_kernel_2d_stacked,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((rp, O), x.dtype),
        interpret=interpret,
    )(ly, x, q, s)
    return out[:rows] if pad else out


def pick_block(dim: int) -> int | None:
    for b in _BLOCK_CANDIDATES:
        if dim % b == 0:
            return b
    return None


def _pick_1d_bo(rp: int, H: int, O: int, x_itemsize: int,
                stripe_rows: int | None = None) -> int | None:
    """Output-block width for the 1D whole-contraction grid, or None to
    use the 2D grid: x [rp, H] must fit the VMEM x-budget and the
    [stripe_rows, bo] weight stripe the stripe budget (stripe_rows
    defaults to H — int8's byte rows; the int4 path passes H/2, its
    PACKED byte rows). The per-hidden-size _TILE_TABLE caps bo below the
    budget-driven choice where measurement says shallower grids lose to
    XLA. Shared by the stacked and unstacked kernels of both precisions
    so identical shapes always pick identical grids."""
    if rp * H * x_itemsize > _X_VMEM_BUDGET_BYTES:
        return None
    sr = H if stripe_rows is None else stripe_rows
    bo = pick_block(O)
    cap = _TILE_TABLE.get(H)
    if cap is not None and bo is not None and bo > cap and O % cap == 0:
        bo = cap
    while bo is not None and sr * bo > _STRIPE_BUDGET_BYTES:
        bo = next((b for b in _BLOCK_CANDIDATES
                   if b < bo and O % b == 0), None)
    return bo


def pick_int4_bo(rows: int, H: int, O: int, ng: int,
                 x_itemsize: int) -> int | None:
    """Output-block width for the w4a16 1D whole-stripe kernel, or None
    -> models/quant.mm takes the dequant XLA fallback. Preconditions on
    top of the shared budgets: an even group count (the split-half
    packing pairs lo/hi groups per byte row) and 128-aligned groups
    (the kernel's x slices must be lane-aligned; G=64 shapes fall back).
    """
    if ng <= 0 or ng % 2 or H % ng:
        return None
    if (H // ng) % 128:
        return None
    rp = rows + ((-rows) % 8)
    return _pick_1d_bo(rp, H, O, x_itemsize, stripe_rows=H // 2)


@functools.partial(jax.jit, static_argnames=("interpret",))
def quant_matmul(x: jax.Array, q: jax.Array, s: jax.Array,
                 *, interpret: bool = False) -> jax.Array:
    """``(x @ dequant(q, s))`` with int8-only HBM weight traffic.

    x: [rows, H] (rows padded to a multiple of 8 here if needed);
    q: [H, O] int8; s: [1, O] f32 per-output-channel scales (the
    models/quant.QTensor layout). Returns [rows, O] in x.dtype.
    Caller guarantees H and O are divisible by a block candidate
    (models/quant.mm falls back to the XLA path otherwise).
    """
    rows, H = x.shape
    O = q.shape[1]
    bh, bo = pick_block(H), pick_block(O)
    if bh is None or bo is None:
        raise ValueError(f"no block divides H={H} / O={O}; use the XLA path")
    pad = (-rows) % 8
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    rp = rows + pad

    # Prefer the 1D whole-contraction grid: shrink bo until the [H, bo]
    # int8 stripe fits the VMEM budget (keeping bo a divisor of O).
    bo_1d = _pick_1d_bo(rp, H, O, x.dtype.itemsize)
    if bo_1d is not None:
        out = pl.pallas_call(
            _qmm_kernel_1d,
            grid=(O // bo_1d,),
            in_specs=[
                pl.BlockSpec((rp, H), lambda i: (0, 0)),
                pl.BlockSpec((H, bo_1d), lambda i: (0, i)),
                pl.BlockSpec((1, bo_1d), lambda i: (0, i)),
            ],
            out_specs=pl.BlockSpec((rp, bo_1d), lambda i: (0, i)),
            out_shape=jax.ShapeDtypeStruct((rp, O), x.dtype),
            interpret=interpret,
        )(x, q, s)
        return out[:rows] if pad else out

    out = pl.pallas_call(
        _qmm_kernel,
        grid=(O // bo, H // bh),
        in_specs=[
            pl.BlockSpec((rp, bh), lambda i, j: (0, j)),
            pl.BlockSpec((bh, bo), lambda i, j: (j, i)),
            pl.BlockSpec((1, bo), lambda i, j: (0, i)),
        ],
        out_specs=pl.BlockSpec((rp, bo), lambda i, j: (0, i)),
        scratch_shapes=[pltpu.VMEM((rp, bo), jnp.float32)],
        out_shape=jax.ShapeDtypeStruct((rp, O), x.dtype),
        interpret=interpret,
    )(x, q, s)
    return out[:rows] if pad else out


@functools.partial(jax.jit, static_argnames=("interpret",))
def quant_matmul4(x: jax.Array, q: jax.Array, s: jax.Array,
                  *, interpret: bool = False) -> jax.Array:
    """``x @ dequant4(q, s)`` with int4-PACKED HBM weight traffic.

    x: [rows, H]; q: [H/2, O] int8 packed nibbles (models/quant.pack4's
    split-half layout); s: [ng, O] f32 group scales. Returns [rows, O]
    in x.dtype. Caller guarantees :func:`pick_int4_bo` accepts the shape
    (models/quant.mm falls back to the dequant XLA path otherwise).
    """
    rows, H = x.shape
    O = q.shape[1]
    ng = s.shape[0]
    bo = pick_int4_bo(rows, H, O, ng, x.dtype.itemsize)
    if bo is None:
        raise ValueError(
            f"w4a16 kernel does not cover H={H} O={O} ng={ng}; use the "
            "XLA fallback (models/quant.mm gates on pick_int4_bo)")
    pad = (-rows) % 8
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    rp = rows + pad
    out = pl.pallas_call(
        _qmm4_kernel_1d,
        grid=(O // bo,),
        in_specs=[
            pl.BlockSpec((rp, H), lambda i: (0, 0)),
            pl.BlockSpec((H // 2, bo), lambda i: (0, i)),
            pl.BlockSpec((ng, bo), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((rp, bo), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((rp, O), x.dtype),
        interpret=interpret,
    )(x, q, s)
    return out[:rows] if pad else out


@functools.partial(jax.jit, static_argnames=("interpret",))
def quant_matmul_stacked4(x: jax.Array, q: jax.Array, s: jax.Array,
                          layer: jax.Array, *,
                          interpret: bool = False) -> jax.Array:
    """``x @ dequant4(q[layer], s[layer])`` reading the stacked packed
    pool directly — the int4 twin of :func:`quant_matmul_stacked`.

    x: [rows, H]; q: [L, H/2, O] int8 packed nibbles; s: [L, ng, O] f32
    group scales (the stacked models/quant.QTensor4 layout); layer:
    scalar int32. Same coverage contract as :func:`quant_matmul4`.
    """
    rows, H = x.shape
    O = q.shape[2]
    ng = s.shape[1]
    bo = pick_int4_bo(rows, H, O, ng, x.dtype.itemsize)
    if bo is None:
        raise ValueError(
            f"w4a16 kernel does not cover H={H} O={O} ng={ng}; use the "
            "XLA fallback (models/quant.mm gates on pick_int4_bo)")
    pad = (-rows) % 8
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    rp = rows + pad
    ly = jnp.asarray(layer, jnp.int32).reshape(1)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(O // bo,),
        in_specs=[
            pl.BlockSpec((rp, H), lambda i, ly: (0, 0)),
            pl.BlockSpec((1, H // 2, bo), lambda i, ly: (ly[0], 0, i)),
            pl.BlockSpec((1, ng, bo), lambda i, ly: (ly[0], 0, i)),
        ],
        out_specs=pl.BlockSpec((rp, bo), lambda i, ly: (0, i)),
    )
    out = pl.pallas_call(
        _qmm4_kernel_1d_stacked,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((rp, O), x.dtype),
        interpret=interpret,
    )(ly, x, q, s)
    return out[:rows] if pad else out
