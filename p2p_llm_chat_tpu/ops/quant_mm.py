"""Pallas w8a16 matmul: int8 weights dequantized in VMEM, not HBM.

Why this kernel exists: XLA on TPU does not stream int8 dot operands —
``x @ q.astype(bf16)`` (and the mixed-dtype ``dot_general``) materialise
a full bf16 copy of the weight in HBM before the matmul, so "int8"
decode read MORE bytes than bf16 (measured on a v5e chip: 22-layer
decode trunk 4.0 ms with the convert vs 2.9 ms plain bf16 — the int8
read + bf16 write + bf16 read round trip). Here each program DMAs an
int8 ``[block_h, block_o]`` weight tile straight into VMEM, converts it
there (VPU, free next to the HBM stream), and feeds the MXU — HBM sees
int8 only, which is the entire point of weight-only quantization for
bandwidth-bound decode (models/quant.py).

Grid ``(O/block_o, H/block_h)`` with the contraction (H) innermost: the
f32 accumulator tile stays resident in VMEM scratch across the H walk
and is scaled (per-output-channel ``s``) once on the last step.

Used by models/quant.mm for small-row calls (decode/verify ticks — the
bandwidth-bound shapes); prefill keeps the XLA path, where the convert
cost is amortised over thousands of rows and the matmul is
compute-bound. ``interpret=True`` runs on CPU for hardware-free parity
tests (tests/test_quant.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Weight-tile candidates, first divisor wins. All lane-aligned (x128) and
# int8-sublane-aligned (x32). Bigger tiles = fewer program invocations
# (the per-program cost is what erodes the bandwidth win at decode);
# 1024x1024 int8 = 1 MiB of VMEM per tile, comfortably resident.
_BLOCK_CANDIDATES = (1024, 512, 256, 128)

# VMEM budget for ONE whole-contraction weight stripe [H, bo] int8 on the
# 1D-grid path (~16 MB VMEM/core; Mosaic double-buffers the stripe, so the
# working set is 2x this, leaving room for x/out/everything else). Chosen
# so bench-1b's w_down (H=5632) still runs whole-H stripes at bo=512.
# QMM_STRIPE_BUDGET overrides (bytes; 0 forces the 2D grid everywhere).
import os as _os

_STRIPE_BUDGET_BYTES = int(_os.environ.get("QMM_STRIPE_BUDGET",
                                           4 * 1024 * 1024))

# Ceiling for the fully-resident x block of the 1D whole-contraction
# grid (x [rows, H] bf16 + two double-buffered weight stripes must fit
# ~16 MB VMEM). Calls above it use the 2D grid, whose x blocks tile over
# H — hit by 512-row prefill-admission chunks at 8B dims (rows x 14336
# bf16 = 14.7 MB, observed as a compile-time VMEM OOM).
_X_VMEM_BUDGET_BYTES = 6 * 1024 * 1024


def _qmm_kernel(x_ref, q_ref, s_ref, o_ref, acc_ref):
    j = pl.program_id(1)
    num_h = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[...]                                 # [rows, bh] bf16
    q = q_ref[...].astype(x.dtype)                 # int8 -> bf16 in VMEM
    acc_ref[:] += jax.lax.dot(x, q, preferred_element_type=jnp.float32)

    @pl.when(j == num_h - 1)
    def _finalise():
        s = s_ref[0].astype(jnp.float32)           # [bo]
        o_ref[...] = (acc_ref[:] * s[None, :]).astype(o_ref.dtype)


def _qmm_kernel_1d(x_ref, q_ref, s_ref, o_ref):
    """Whole-contraction stripe: one program = one [H, bo] weight tile =
    one output tile — no revisits, no scratch accumulator, and ~3x fewer
    program invocations than the 2D grid at decode shapes (measured: the
    per-program fixed cost, not DMA bandwidth, dominated the 2D walk)."""
    x = x_ref[...]                                 # [rows, H] bf16
    q = q_ref[...].astype(x.dtype)                 # int8 -> bf16 in VMEM
    acc = jax.lax.dot(x, q, preferred_element_type=jnp.float32)
    s = s_ref[0].astype(jnp.float32)               # [bo]
    o_ref[...] = (acc * s[None, :]).astype(o_ref.dtype)


def _qmm_kernel_1d_stacked(layer_ref, x_ref, q_ref, s_ref, o_ref):
    """Whole-contraction stripe fetched from the STACKED [L, H, O] weight
    at the scalar-prefetched layer index. This is how the decode scan
    avoids materialising per-layer weight slices: a pallas custom-call
    cannot alias a dynamic-slice view, so feeding it sliced operands made
    XLA copy every layer's int8 weights before the matmul — measured at
    ~1.9 ms of a 3.8 ms bench-1b step (half the step!). With the stacked
    operand the kernel DMAs tiles straight from the scan-invariant pool."""
    x = x_ref[...]                                 # [rows, H] bf16
    q = q_ref[0].astype(x.dtype)                   # [H, bo] int8 -> bf16
    acc = jax.lax.dot(x, q, preferred_element_type=jnp.float32)
    s = s_ref[0, 0].astype(jnp.float32)            # [bo]
    o_ref[...] = (acc * s[None, :]).astype(o_ref.dtype)


def _qmm_kernel_2d_stacked(layer_ref, x_ref, q_ref, s_ref, o_ref, acc_ref):
    j = pl.program_id(1)
    num_h = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[...]                                 # [rows, bh]
    q = q_ref[0].astype(x.dtype)                   # [bh, bo]
    acc_ref[:] += jax.lax.dot(x, q, preferred_element_type=jnp.float32)

    @pl.when(j == num_h - 1)
    def _finalise():
        s = s_ref[0, 0].astype(jnp.float32)        # [bo]
        o_ref[...] = (acc_ref[:] * s[None, :]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def quant_matmul_stacked(x: jax.Array, q: jax.Array, s: jax.Array,
                         layer: jax.Array, *,
                         interpret: bool = False) -> jax.Array:
    """``x @ dequant(q[layer], s[layer])`` reading the stacked weight
    directly — no per-layer slice copy (see _qmm_kernel_1d_stacked).

    x: [rows, H]; q: [L, H, O] int8; s: [L, 1, O] f32 (the stacked
    models/quant.QTensor layout); layer: scalar int32. Same block
    preconditions as :func:`quant_matmul`.
    """
    rows, H = x.shape
    O = q.shape[2]
    bh, bo = pick_block(H), pick_block(O)
    if bh is None or bo is None:
        raise ValueError(f"no block divides H={H} / O={O}; use the XLA path")
    pad = (-rows) % 8
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    rp = rows + pad
    ly = jnp.asarray(layer, jnp.int32).reshape(1)

    bo_1d = _pick_1d_bo(rp, H, O, x.dtype.itemsize)
    if bo_1d is not None:
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(O // bo_1d,),
            in_specs=[
                pl.BlockSpec((rp, H), lambda i, ly: (0, 0)),
                pl.BlockSpec((1, H, bo_1d), lambda i, ly: (ly[0], 0, i)),
                pl.BlockSpec((1, 1, bo_1d), lambda i, ly: (ly[0], 0, i)),
            ],
            out_specs=pl.BlockSpec((rp, bo_1d), lambda i, ly: (0, i)),
        )
        out = pl.pallas_call(
            _qmm_kernel_1d_stacked,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((rp, O), x.dtype),
            interpret=interpret,
        )(ly, x, q, s)
        return out[:rows] if pad else out

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(O // bo, H // bh),
        in_specs=[
            pl.BlockSpec((rp, bh), lambda i, j, ly: (0, j)),
            pl.BlockSpec((1, bh, bo), lambda i, j, ly: (ly[0], j, i)),
            pl.BlockSpec((1, 1, bo), lambda i, j, ly: (ly[0], 0, i)),
        ],
        out_specs=pl.BlockSpec((rp, bo), lambda i, j, ly: (0, i)),
        scratch_shapes=[pltpu.VMEM((rp, bo), jnp.float32)],
    )
    out = pl.pallas_call(
        _qmm_kernel_2d_stacked,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((rp, O), x.dtype),
        interpret=interpret,
    )(ly, x, q, s)
    return out[:rows] if pad else out


def pick_block(dim: int) -> int | None:
    for b in _BLOCK_CANDIDATES:
        if dim % b == 0:
            return b
    return None


def _pick_1d_bo(rp: int, H: int, O: int, x_itemsize: int) -> int | None:
    """Output-block width for the 1D whole-contraction grid, or None to
    use the 2D grid: x [rp, H] must fit the VMEM x-budget and the [H, bo]
    int8 stripe the stripe budget (shared by the stacked and unstacked
    kernels so identical shapes always pick identical grids)."""
    if rp * H * x_itemsize > _X_VMEM_BUDGET_BYTES:
        return None
    bo = pick_block(O)
    while bo is not None and H * bo > _STRIPE_BUDGET_BYTES:
        bo = next((b for b in _BLOCK_CANDIDATES
                   if b < bo and O % b == 0), None)
    return bo


@functools.partial(jax.jit, static_argnames=("interpret",))
def quant_matmul(x: jax.Array, q: jax.Array, s: jax.Array,
                 *, interpret: bool = False) -> jax.Array:
    """``(x @ dequant(q, s))`` with int8-only HBM weight traffic.

    x: [rows, H] (rows padded to a multiple of 8 here if needed);
    q: [H, O] int8; s: [1, O] f32 per-output-channel scales (the
    models/quant.QTensor layout). Returns [rows, O] in x.dtype.
    Caller guarantees H and O are divisible by a block candidate
    (models/quant.mm falls back to the XLA path otherwise).
    """
    rows, H = x.shape
    O = q.shape[1]
    bh, bo = pick_block(H), pick_block(O)
    if bh is None or bo is None:
        raise ValueError(f"no block divides H={H} / O={O}; use the XLA path")
    pad = (-rows) % 8
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    rp = rows + pad

    # Prefer the 1D whole-contraction grid: shrink bo until the [H, bo]
    # int8 stripe fits the VMEM budget (keeping bo a divisor of O).
    bo_1d = _pick_1d_bo(rp, H, O, x.dtype.itemsize)
    if bo_1d is not None:
        out = pl.pallas_call(
            _qmm_kernel_1d,
            grid=(O // bo_1d,),
            in_specs=[
                pl.BlockSpec((rp, H), lambda i: (0, 0)),
                pl.BlockSpec((H, bo_1d), lambda i: (0, i)),
                pl.BlockSpec((1, bo_1d), lambda i: (0, i)),
            ],
            out_specs=pl.BlockSpec((rp, bo_1d), lambda i: (0, i)),
            out_shape=jax.ShapeDtypeStruct((rp, O), x.dtype),
            interpret=interpret,
        )(x, q, s)
        return out[:rows] if pad else out

    out = pl.pallas_call(
        _qmm_kernel,
        grid=(O // bo, H // bh),
        in_specs=[
            pl.BlockSpec((rp, bh), lambda i, j: (0, j)),
            pl.BlockSpec((bh, bo), lambda i, j: (j, i)),
            pl.BlockSpec((1, bo), lambda i, j: (0, i)),
        ],
        out_specs=pl.BlockSpec((rp, bo), lambda i, j: (0, i)),
        scratch_shapes=[pltpu.VMEM((rp, bo), jnp.float32)],
        out_shape=jax.ShapeDtypeStruct((rp, O), x.dtype),
        interpret=interpret,
    )(x, q, s)
    return out[:rows] if pad else out
