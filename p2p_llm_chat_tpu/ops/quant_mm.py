"""Pallas w8a16 matmul: int8 weights dequantized in VMEM, not HBM.

Why this kernel exists: XLA on TPU does not stream int8 dot operands —
``x @ q.astype(bf16)`` (and the mixed-dtype ``dot_general``) materialise
a full bf16 copy of the weight in HBM before the matmul, so "int8"
decode read MORE bytes than bf16 (measured on a v5e chip: 22-layer
decode trunk 4.0 ms with the convert vs 2.9 ms plain bf16 — the int8
read + bf16 write + bf16 read round trip). Here each program DMAs an
int8 ``[block_h, block_o]`` weight tile straight into VMEM, converts it
there (VPU, free next to the HBM stream), and feeds the MXU — HBM sees
int8 only, which is the entire point of weight-only quantization for
bandwidth-bound decode (models/quant.py).

Grid ``(O/block_o, H/block_h)`` with the contraction (H) innermost: the
f32 accumulator tile stays resident in VMEM scratch across the H walk
and is scaled (per-output-channel ``s``) once on the last step.

Used by models/quant.mm for small-row calls (decode/verify ticks — the
bandwidth-bound shapes); prefill keeps the XLA path, where the convert
cost is amortised over thousands of rows and the matmul is
compute-bound. ``interpret=True`` runs on CPU for hardware-free parity
tests (tests/test_quant.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Weight-tile candidates, first divisor wins. All lane-aligned (x128) and
# int8-sublane-aligned (x32). Bigger tiles = fewer program invocations
# (the per-program cost is what erodes the bandwidth win at decode);
# 1024x1024 int8 = 1 MiB of VMEM per tile, comfortably resident.
_BLOCK_CANDIDATES = (1024, 512, 256, 128)


def _qmm_kernel(x_ref, q_ref, s_ref, o_ref, acc_ref):
    j = pl.program_id(1)
    num_h = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[...]                                 # [rows, bh] bf16
    q = q_ref[...].astype(x.dtype)                 # int8 -> bf16 in VMEM
    acc_ref[:] += jax.lax.dot(x, q, preferred_element_type=jnp.float32)

    @pl.when(j == num_h - 1)
    def _finalise():
        s = s_ref[0].astype(jnp.float32)           # [bo]
        o_ref[...] = (acc_ref[:] * s[None, :]).astype(o_ref.dtype)


def pick_block(dim: int) -> int | None:
    for b in _BLOCK_CANDIDATES:
        if dim % b == 0:
            return b
    return None


@functools.partial(jax.jit, static_argnames=("interpret",))
def quant_matmul(x: jax.Array, q: jax.Array, s: jax.Array,
                 *, interpret: bool = False) -> jax.Array:
    """``(x @ dequant(q, s))`` with int8-only HBM weight traffic.

    x: [rows, H] (rows padded to a multiple of 8 here if needed);
    q: [H, O] int8; s: [1, O] f32 per-output-channel scales (the
    models/quant.QTensor layout). Returns [rows, O] in x.dtype.
    Caller guarantees H and O are divisible by a block candidate
    (models/quant.mm falls back to the XLA path otherwise).
    """
    rows, H = x.shape
    O = q.shape[1]
    bh, bo = pick_block(H), pick_block(O)
    if bh is None or bo is None:
        raise ValueError(f"no block divides H={H} / O={O}; use the XLA path")
    pad = (-rows) % 8
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    rp = rows + pad

    out = pl.pallas_call(
        _qmm_kernel,
        grid=(O // bo, H // bh),
        in_specs=[
            pl.BlockSpec((rp, bh), lambda i, j: (0, j)),
            pl.BlockSpec((bh, bo), lambda i, j: (j, i)),
            pl.BlockSpec((1, bo), lambda i, j: (0, i)),
        ],
        out_specs=pl.BlockSpec((rp, bo), lambda i, j: (0, i)),
        scratch_shapes=[pltpu.VMEM((rp, bo), jnp.float32)],
        out_shape=jax.ShapeDtypeStruct((rp, O), x.dtype),
        interpret=interpret,
    )(x, q, s)
    return out[:rows] if pad else out
