"""Pallas TPU kernels and the paged KV-cache machinery.

The north-star serving path (BASELINE.json; SURVEY.md §7 stage 4) replaces
the dense ``[L, B, max_seq, Hkv, D]`` cache — whose HBM footprint reserves
``max_seq`` slots for every batch row — with a paged pool: fixed-size pages
allocated per request for its *actual* context budget, addressed through a
page table, laid out token-major so pages read/write as contiguous blocks.
Decode attention over the pool has two equal-speed implementations
(ops/paged_attention.py): a page-granular gather + fused dense attend
(default) and a Pallas flash-decode kernel walking scalar-prefetched
page-table indices — either way HBM reads scale with live context, never
with allocation.

Modules:
- :mod:`.paged_kv` — PagedKVCache pytree, host-side page allocator, and the
  pure-JAX page write/gather ops.
- :mod:`.paged_attention` — paged decode attention (gather + Pallas kernel,
  with a jnp reference oracle and CPU ``interpret=True`` support for
  hardware-free tests, per SURVEY.md §4).
- :mod:`.quant_mm` — Pallas w8a16 matmul streaming int8 weights through
  VMEM dequant (models/quant.py's decode path; XLA alone materialises a
  bf16 weight copy, defeating the bandwidth win).
"""

from .paged_kv import PagedKVCache, PageAllocator
from .paged_attention import paged_attention, paged_attention_reference
from .quant_mm import quant_matmul

__all__ = ["PagedKVCache", "PageAllocator", "paged_attention",
           "paged_attention_reference", "quant_matmul"]
