"""Pallas TPU kernels and the paged KV-cache machinery.

The north-star serving path (BASELINE.json; SURVEY.md §7 stage 4) replaces
the dense ``[L, B, max_seq, Hkv, D]`` cache — whose HBM footprint reserves
``max_seq`` slots for every batch row — with a paged pool: fixed-size pages
allocated per request for its *actual* context budget, addressed through a
page table. Decode attention over the paged pool is a Pallas flash-decode
kernel (ops/paged_attention.py) whose page fetches are driven by
scalar-prefetched page-table indices, so HBM reads scale with live context,
never with allocation.

Modules:
- :mod:`.paged_kv` — PagedKVCache pytree, host-side page allocator, and the
  pure-JAX page write/gather ops.
- :mod:`.paged_attention` — the Pallas decode-attention kernel (with a jnp
  reference oracle and CPU ``interpret=True`` support for hardware-free
  tests, per SURVEY.md §4).
"""

from .paged_kv import PagedKVCache, PageAllocator
from .paged_attention import paged_attention, paged_attention_reference

__all__ = ["PagedKVCache", "PageAllocator", "paged_attention",
           "paged_attention_reference"]
