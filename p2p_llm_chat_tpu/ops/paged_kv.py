"""Paged KV cache: page pool, page table, allocator, and write ops.

Replaces the dense cache's per-row ``max_seq`` reservation (models/llama.py
KVCache) with fixed-size pages drawn from a shared pool, so HBM holds the
sum of live context budgets instead of ``num_slots x max_seq``. The pool
layout is **token-major within a page**:

    k/v: [L, num_pages, page_size, Hkv, D]

— one token's kv is a contiguous ``[Hkv, D]`` window and one page is a
contiguous ``[page_size, Hkv, D]`` block, exactly the dense cache's slot
order. That makes the decode write a dense-shaped scatter, the admission
splice a transpose-free reshape, and a whole-page gather a contiguous
block read (ops/paged_attention.py's default gather path) — measured ~10x
faster end-to-end than the earlier head-major layout, whose strided
windows made XLA scatters and per-(head,page) kernel programs dominate
the decode tick. Page 0 is a permanent garbage bin: padded prefill slots
and parked decode rows write there, so masked writes never need a branch
(the overwrite-before-trust invariant of the dense path becomes a
write-to-trash invariant here).

All device-side state is a pytree (works as a jit carry / donated arg);
the allocator is host-side bookkeeping owned by the scheduler thread.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..models.configs import ModelConfig


class PagedKVCache(NamedTuple):
    """k/v: [L, num_pages, page_size, Hkv, D]; page_table: [B, max_pages]
    (physical page id per logical page; unused entries MUST hold 0 — the
    garbage page — so kernel-side fetches of dead pages stay in bounds);
    lengths: [B] live tokens per row.

    Quantized pool (``create(..., quantized=True)``): k/v store int8 with
    per-(layer, slot, kv-head) float32 scales ``k_scale``/``v_scale``,
    stored HEAD-MAJOR as ``[L, num_pages, Hkv, page_size]`` — symmetric
    over the head_dim axis, the same scheme models/quant.py uses over
    matmul contractions. Decode attention is KV-bandwidth-bound, so int8
    halves the dominant read (measured ~0.3 ms off a B=32 bench-1b step
    on v5e) and doubles how much context one pool holds; the scales fold
    into k/v at the in-register dequant, so the MXU still consumes the
    int8 stream directly. bf16 pools keep scale = None.

    Why head-major: the decode append kernel
    (ops/paged_attention._append_kernel) DMAs one page's scales per
    (kv-head) as a contiguous ``[page_size]`` lane vector and folds them
    into the VMEM dequant — with Hkv (= 8) as the minor dim that slice is
    strided 8 ways, a shape Mosaic cannot form. It also keeps the minor
    dim >= a half-lane (64+) so XLA does not answer the decode scatter /
    attention gather pair with transposed layouts and full-array copies
    (an earlier slot-minor layout cost ~0.4 ms/step of pure layout
    conversion). ``k_scale_view``/``v_scale_view`` return the logical
    [L, N, ps, Hkv] order for oracles/tests.
    """

    k: jax.Array
    v: jax.Array
    page_table: jax.Array
    lengths: jax.Array
    k_scale: Optional[jax.Array] = None
    v_scale: Optional[jax.Array] = None

    @property
    def page_size(self) -> int:
        return self.k.shape[2]

    @property
    def num_pages(self) -> int:
        return self.k.shape[1]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    @property
    def max_pages_per_row(self) -> int:
        return self.page_table.shape[1]

    @property
    def k_scale_view(self) -> jax.Array:
        """k_scale in logical [L, N, page_size, Hkv] order (transposed,
        lane-padding sliced off the head-major storage)."""
        return self.k_scale[..., : self.page_size].transpose(0, 1, 3, 2)

    @property
    def v_scale_view(self) -> jax.Array:
        return self.v_scale[..., : self.page_size].transpose(0, 1, 3, 2)

    @classmethod
    def create(cls, config: ModelConfig, batch: int, num_pages: int,
               page_size: int, max_pages_per_row: Optional[int] = None,
               dtype=jnp.bfloat16, quantized: bool = False,
               mesh=None) -> "PagedKVCache":
        shape = (config.num_layers, num_pages, page_size,
                 config.num_kv_heads, config.head_dim)
        if max_pages_per_row is None:
            max_pages_per_row = num_pages
        if quantized:
            # Minor dim padded to a full 128-lane tile: Mosaic DMAs of a
            # [Hkv, ps] scale page must be lane-aligned (ps = 64 is half
            # a tile). Slots past page_size are never written or read.
            ps_pad = -(-page_size // 128) * 128
            sshape = (config.num_layers, num_pages,
                      config.num_kv_heads, ps_pad)
            cache = cls(
                k=jnp.zeros(shape, jnp.int8), v=jnp.zeros(shape, jnp.int8),
                page_table=jnp.zeros((batch, max_pages_per_row), jnp.int32),
                lengths=jnp.zeros((batch,), jnp.int32),
                k_scale=jnp.zeros(sshape, jnp.float32),
                v_scale=jnp.zeros(sshape, jnp.float32),
            )
        else:
            cache = cls(
                k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
                page_table=jnp.zeros((batch, max_pages_per_row), jnp.int32),
                lengths=jnp.zeros((batch,), jnp.int32),
            )
        if mesh is not None:
            cache = shard_cache(cache, mesh)
        return cache


def shard_cache(cache: PagedKVCache, mesh,
                tp_axis: str = "tp") -> PagedKVCache:
    """Shard the pool over kv heads (tp) — the memory-fit half of the
    tensor-parallel serving story: without it every chip holds the FULL
    pool and TP cannot serve contexts one chip's HBM can't (VERDICT r3
    weak #3). k/v shard dim 3 (Hkv of [L, N, ps, Hkv, D]); the head-major
    scale arrays shard dim 2; page_table/lengths replicate (host-written
    per tick). Falls back to replication when Hkv doesn't divide tp
    (tiny test configs — same policy as parallel/sharding.constrain)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    if tp_axis not in mesh.shape:
        return cache
    t = mesh.shape[tp_axis]
    hkv = cache.k.shape[3]
    ax = tp_axis if t > 1 and hkv % t == 0 else None

    def put(arr, spec):
        return jax.device_put(arr, NamedSharding(mesh, spec))

    rep = P()
    out = cache._replace(
        k=put(cache.k, P(None, None, None, ax)),
        v=put(cache.v, P(None, None, None, ax)),
        page_table=put(cache.page_table, rep),
        lengths=put(cache.lengths, rep),
    )
    if cache.quantized:
        out = out._replace(
            k_scale=put(cache.k_scale, P(None, None, ax)),
            v_scale=put(cache.v_scale, P(None, None, ax)))
    return out


def quant_kv(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric int8 over the trailing head_dim axis: x [..., Hkv, D] ->
    (int8 [..., Hkv, D], f32 scale [..., Hkv]). (bf16 scales were tried
    to shrink the while-carry layout copies; the bf16 scale GATHER is
    ~5x slower than f32's on v5e and regressed the step — f32 stays.)"""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    s = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(xf / s[..., None]), -127, 127).astype(jnp.int8)
    return q, s


class PageAllocator:
    """Host-side free-list over physical pages 1..num_pages-1 (page 0 is
    the shared garbage bin and is never handed out). Owned by the
    scheduler thread; no locking needed there (SURVEY.md §5 single-thread
    scheduler discipline)."""

    def __init__(self, num_pages: int, page_size: int) -> None:
        if num_pages < 2:
            raise ValueError("need >= 2 pages (page 0 is reserved)")
        self.page_size = page_size
        self.num_pages = num_pages
        self._free: list[int] = list(range(num_pages - 1, 0, -1))

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def pages_for(self, tokens: int) -> int:
        """Pages needed to hold ``tokens`` slots."""
        return max(1, -(-tokens // self.page_size))

    def alloc(self, n: int) -> Optional[list[int]]:
        """n physical pages, or None if the pool can't satisfy it (caller
        backpressures — the request waits, nothing is partially held)."""
        if n <= 0:
            raise ValueError(f"alloc({n}): need a positive page count")
        if n > len(self._free):
            return None
        taken = self._free[-n:]
        del self._free[-n:]
        return taken

    def free(self, pages: list[int]) -> None:
        for p in pages:
            if not 0 < p < self.num_pages:
                raise ValueError(f"freeing invalid page {p}")
        self._free.extend(pages)


# -- device-side write ops (pure JAX; used inside jitted serving programs) ----

def _scatter_kv(cache: PagedKVCache, new_k: jax.Array, new_v: jax.Array,
                scatter, sscatter=None) -> PagedKVCache:
    """Apply ``scatter(pool_array, update)`` to k and v — quantizing the
    updates (and scattering their scales via ``sscatter``, the
    head-major [L, N, Hkv, ps] twin of the pool index expression) when
    the pool is int8. Centralises the only difference between the bf16
    and quantized write paths."""
    if not cache.quantized:
        return cache._replace(k=scatter(cache.k, new_k),
                              v=scatter(cache.v, new_v))
    qk, sk = quant_kv(new_k)
    qv, sv = quant_kv(new_v)
    return cache._replace(
        k=scatter(cache.k, qk), v=scatter(cache.v, qv),
        k_scale=sscatter(cache.k_scale, sk),
        v_scale=sscatter(cache.v_scale, sv))


def write_prefill(cache: PagedKVCache, layer_k: jax.Array, layer_v: jax.Array,
                  rows: jax.Array, lens: jax.Array) -> PagedKVCache:
    """Splice a dense prefill chunk's KV into the pool.

    layer_k/v: [L, R, S, Hkv, D] (the small dense cache a prefill chunk
    produced — serve/scheduler.py admission path); rows: [R] target batch
    rows; lens: [R] valid tokens per chunk row. Positions past ``lens`` are
    routed to garbage page 0 slot 0; valid positions go to the page/slot
    the row's page table maps them to. The row's page_table entries must
    already be set (set_row_table).
    """
    L, R, S, Hkv, D = layer_k.shape
    ps = cache.page_size
    pos = jnp.arange(S)[None, :]                       # [1,S]
    valid = pos < lens[:, None]                        # [R,S]
    logical = pos // ps                                # [1,S] -> broadcast [R,S]
    logical = jnp.broadcast_to(logical, (R, S))
    phys = jnp.take_along_axis(cache.page_table[rows], logical, axis=1)  # [R,S]
    phys = jnp.where(valid, phys, 0)
    slot = jnp.where(valid, jnp.broadcast_to(pos % ps, (R, S)), 0)

    # [L,R,S,Hkv,D] -> scatter at (layer, phys, slot). The advanced
    # indices (phys, slot) are adjacent dims, so the update keeps the
    # array order: [L, R, S, Hkv, D] — no axis shuffling.
    cache = _scatter_kv(cache, layer_k, layer_v,
                        lambda arr, upd: arr.at[:, phys, slot].set(
                            upd, mode="drop"),
                        # head-major scale target; non-adjacent advanced
                        # indices (dims 1, 3) move to the front: update
                        # [R, S, L, Hkv]
                        lambda arr, upd: arr.at[:, phys, :, slot].set(
                            upd.transpose(1, 2, 0, 3), mode="drop"))
    lengths = cache.lengths.at[rows].set(lens.astype(cache.lengths.dtype))
    return cache._replace(lengths=lengths)


def write_prefill_batch(cache: PagedKVCache, chunk_k: jax.Array,
                        chunk_v: jax.Array, rows: jax.Array,
                        lens: jax.Array, tables: jax.Array) -> PagedKVCache:
    """Splice a whole admission chunk's prefill KV into the pool in ONE
    page-granular scatter (serve/scheduler.py hot path).

    Two rejected designs, for the record: R sequential per-row scatters
    made paged admission ~8x slower than dense, and a single *per-token*
    scatter (R*S indices, each a strided [L,Hkv,D] window) barely helped —
    TPU scatters want few indices with large contiguous windows. Here the
    unit is the pool's own page: each (row, logical page) copies one
    [L,<=page_size,Hkv,D] block, so a 32-request x 128-token chunk is 64
    window-copies instead of 4096 strided ones — and with the token-major
    pool layout the chunk->page reshape is free (no transpose).

    chunk_k/v: [L, R, S, Hkv, D] for any S (smaller than one page writes a
    partial leading tile; non-page-aligned S pads the last tile — padded
    slots land past ``lens`` or in garbage page 0, never attended); rows:
    [R] target batch rows, padding entries set to an out-of-range sentinel
    (>= B) so their table/length installs drop; lens: [R] valid tokens;
    tables: [R, max_pages_per_row] physical page ids, zero-padded past
    each row's allocation (and all-zero for padding entries).

    Ordering safety: real rows' allocated pages are disjoint and real row
    indices unique, so the only duplicate scatter index is garbage page 0
    — whose content is garbage by contract either way. Slots past a row's
    ``lens`` inside an *allocated* page receive stale prefill values;
    they are never attended (length-masked) and decode overwrites slot
    ``lengths[b]`` before trusting it — the overwrite-before-trust
    invariant. Logical pages past the allocation land in page 0.
    """
    L, R, S, Hkv, D = chunk_k.shape
    P, ps_eff = _page_tiling(S, cache.page_size)
    phys = tables[:, :P].reshape(R * P).astype(jnp.int32)
    cache = _tile_scatter(cache, chunk_k, chunk_v, phys, P, ps_eff)
    table = cache.page_table.at[rows].set(tables.astype(jnp.int32),
                                          mode="drop")
    lengths = cache.lengths.at[rows].set(lens.astype(cache.lengths.dtype),
                                         mode="drop")
    return cache._replace(page_table=table, lengths=lengths)


def _page_tiling(S: int, ps: int) -> tuple[int, int]:
    """(page tiles P, effective tile width): a sub-page span is one
    partial leading tile; otherwise ceil(S/ps) full-width tiles (the
    last padded by _tile_scatter when S doesn't page-align)."""
    return (1, S) if S < ps else (-(-S // ps), ps)


def _tile_scatter(cache: PagedKVCache, chunk_k: jax.Array,
                  chunk_v: jax.Array, phys: jax.Array, P: int,
                  ps_eff: int) -> PagedKVCache:
    """The page-tile window scatter shared by write_prefill_batch and
    write_prefill_chunk's aligned path: one [L,<=page_size,Hkv,D] copy
    per (row, logical page), ``phys`` [R*P] the physical page per tile.
    Tables/lengths are NOT touched — callers own that install."""
    L, R, S = chunk_k.shape[:3]
    ps = cache.page_size

    # [L,R,S,...] -> [L, R*P, ps_eff, ...]: one pool page per (row,
    # logical page) — a pure reshape under the token-major layout (pads
    # the last tile first when S doesn't page-align).
    def tiles(x):
        if S % ps and S >= ps:
            pad = [(0, 0)] * x.ndim
            pad[2] = (0, P * ps - S)
            x = jnp.pad(x, pad)
        return x.reshape(L, R * P, ps_eff, *x.shape[3:])

    return _scatter_kv(cache, chunk_k, chunk_v,
                       lambda arr, upd: arr.at[:, phys, :ps_eff].set(
                           tiles(upd), mode="drop"),
                       lambda arr, upd: arr.at[:, phys, :, :ps_eff].set(
                           tiles(upd).transpose(0, 1, 3, 2), mode="drop"))


def write_prefill_chunk(cache: PagedKVCache, chunk_k: jax.Array,
                        chunk_v: jax.Array, tables: jax.Array,
                        start: int) -> PagedKVCache:
    """Splice ONE continuation-prefill chunk into the pool — the
    incremental unit of chunked admission (serve/scheduler.py): each
    chunk of a long prompt lands in the pool as it is computed, so the
    final chunk's dispatch splices C tokens, not the whole prompt.

    chunk_k/v: [L, R, C, Hkv, D] covering token positions
    ``start .. start+C`` of each row; tables: [R, max_pages_per_row]
    physical page ids (zero-padded past each row's allocation; all-zero
    for padding entries). Deliberately installs NEITHER tables NOR
    lengths — the scheduler routes every chunk through the ``tables``
    operand and installs the row state atomically with the FINAL chunk,
    so a half-prefilled row never looks live to the decode loop (its
    live page_table row stays zeroed and parked-row garbage writes keep
    landing in page 0 while the chunks accumulate).

    A page-aligned ``start`` (the plain chunk ladder — chunk budgets are
    power-of-two and >= the default page size) takes
    :func:`write_prefill_batch`'s page-tile scatter shifted by
    ``start // page_size``; an unaligned start (a prefix-offset chunk —
    the broadcast prefix shifts every boundary by the registered prefix
    length — or a sub-page chunk budget) falls back to a per-token
    scatter. Positions past a row's allocation hit zero table entries
    (or the width clamp) and land in garbage page 0 — the containment
    write_prefill_batch documents."""
    L, R, C, Hkv, D = chunk_k.shape
    ps = cache.page_size
    if start % ps == 0:
        P, ps_eff = _page_tiling(C, ps)
        lp = start // ps + jnp.arange(P)               # logical pages
        idx = jnp.minimum(lp, tables.shape[1] - 1)
        phys = jnp.where((lp < tables.shape[1])[None, :],
                         tables.astype(jnp.int32)[:, idx], 0)
        phys = phys.reshape(R * P)
        return _tile_scatter(cache, chunk_k, chunk_v, phys, P, ps_eff)
    # Mid-page start: per-token indices (write_prefill's shape) with the
    # chunk's position offset; slower than page tiles but only the
    # prefix-offset chunks pay it.
    pos = start + jnp.arange(C)                        # [C]
    logical = pos // ps
    safe = jnp.minimum(logical, tables.shape[1] - 1)
    phys = jnp.take_along_axis(tables.astype(jnp.int32),
                               jnp.broadcast_to(safe[None, :], (R, C)),
                               axis=1)                 # [R,C]
    phys = jnp.where((logical < tables.shape[1])[None, :], phys, 0)
    slot = jnp.broadcast_to((pos % ps)[None, :], (R, C))
    return _scatter_kv(cache, chunk_k, chunk_v,
                       lambda arr, upd: arr.at[:, phys, slot].set(
                           upd, mode="drop"),
                       # head-major scale target; advanced dims 1, 3 ->
                       # front: update [R, C, L, Hkv]
                       lambda arr, upd: arr.at[:, phys, :, slot].set(
                           upd.transpose(1, 2, 0, 3), mode="drop"))


def write_prefill_row(cache: PagedKVCache, row_k: jax.Array,
                      row_v: jax.Array, row: jax.Array, length: jax.Array,
                      table_row: jax.Array) -> PagedKVCache:
    """Splice ONE request's prefill KV into the pool and install its page
    map — the admission-program unit (serve/scheduler.py unrolls R of
    these sequentially, so later real entries overwrite earlier padding
    entries deterministically; padding entries pass an all-zero
    ``table_row`` so their writes land in garbage page 0).

    row_k/v: [L, S, Hkv, D]; row: scalar target batch row; length: scalar
    valid tokens; table_row: [max_pages_per_row] physical page ids.
    """
    L, S, Hkv, D = row_k.shape
    ps = cache.page_size
    pos = jnp.arange(S)
    valid = pos < length
    phys = jnp.where(valid, table_row[pos // ps], 0)   # [S]
    slot = jnp.where(valid, pos % ps, 0)
    # cache.k: [L, N, ps, Hkv, D]; adjacent advanced indices (phys, slot)
    # keep the update in array order: [L, S, Hkv, D] = row_k as-is.
    cache = _scatter_kv(cache, row_k, row_v,
                        lambda arr, upd: arr.at[:, phys, slot].set(upd),
                        # update [S, L, Hkv] (advanced dims 1, 3 -> front)
                        lambda arr, upd: arr.at[:, phys, :, slot].set(
                            upd.transpose(1, 0, 2)))
    table = cache.page_table.at[row].set(table_row.astype(jnp.int32))
    lengths = cache.lengths.at[row].set(length.astype(cache.lengths.dtype))
    return cache._replace(page_table=table, lengths=lengths)


def write_decode(cache: PagedKVCache, layer: jax.Array, k: jax.Array,
                 v: jax.Array) -> PagedKVCache:
    """Write one decode step's k/v for every row into its current slot.

    k/v: [B, Hkv, D]; row b writes page ``page_table[b, lengths[b]//ps]``
    slot ``lengths[b] % ps`` of ``layer``. Parked rows (whose length the
    caller will not advance) overwrite the same slot next step — and their
    page-table entry for a never-grown row is 0, the garbage bin.
    """
    B = k.shape[0]
    ps = cache.page_size
    logical = cache.lengths // ps                      # [B]
    phys = jnp.take_along_axis(cache.page_table, logical[:, None],
                               axis=1)[:, 0]           # [B]
    slot = cache.lengths % ps
    return _scatter_kv(cache, k, v,
                       lambda arr, upd: arr.at[layer, phys, slot].set(
                           upd, mode="drop"),
                       # layer-sliced target [N, Hkv, ps]; advanced dims
                       # 0, 2 -> update [B, Hkv] as-is
                       lambda arr, upd: arr.at[layer, phys, :, slot].set(
                           upd, mode="drop"))


def write_decode_all_layers(cache: PagedKVCache, k_all: jax.Array,
                            v_all: jax.Array) -> PagedKVCache:
    """Write one decode step's k/v for EVERY layer in one scatter.

    k_all/v_all: [L, B, Hkv, D] (the decode scan's stacked per-layer
    outputs). Row b writes page ``page_table[b, lengths[b]//ps]`` slot
    ``lengths[b] % ps`` across all L layers — one [B]-indexed scatter
    with [L, Hkv, D] windows instead of L scatters with [Hkv, D]
    windows (models/llama.decode_step_paged pairs this with
    ops/paged_attention.paged_attention_append, which folds the current
    token into attention before it lands in the pool). Same garbage-page
    routing as :func:`write_decode`.
    """
    ps = cache.page_size
    logical = cache.lengths // ps                      # [B]
    phys = jnp.take_along_axis(cache.page_table, logical[:, None],
                               axis=1)[:, 0]           # [B]
    slot = cache.lengths % ps
    # Advanced indices (phys, slot) sit on adjacent dims, so the update
    # keeps array order: [L, B, Hkv, D] (and [L, B, Hkv] for scales).
    return _scatter_kv(cache, k_all, v_all,
                       lambda arr, upd: arr.at[:, phys, slot].set(
                           upd, mode="drop"),
                       # update [B, L, Hkv] (advanced dims 1, 3 -> front)
                       lambda arr, upd: arr.at[:, phys, :, slot].set(
                           upd.transpose(1, 0, 2), mode="drop"))


def write_decode_burst(cache: PagedKVCache, k_all: jax.Array,
                       v_all: jax.Array, inc: jax.Array) -> PagedKVCache:
    """Land one decode step for the whole stack and advance: scatter
    every layer's k/v at each row's current slot
    (:func:`write_decode_all_layers`) and bump ``lengths`` by ``inc``
    ([B] int32 — the active mask; parked rows hold position so their
    next write overwrites the same slot).

    This is the per-step mutation both the plain decode tick and the
    fused multi-step scan body (models/llama.decode_fused — K of these
    back to back inside one dispatch) run, kept as ONE function so the
    write/advance ordering cannot drift between the paths: the advance
    must follow the scatter, or a fused step would write its token one
    slot deep and the K-fused-ticks ≡ K-plain-ticks contract breaks.

    Rejected alternative, for the record: carrying the fused tick's K
    tokens in-register and landing them ONCE via
    :func:`write_decode_multi_all_layers` (the spec-verify multi-token
    append) would save K-1 pool scatters — but on int8 pools the later
    steps would then attend EARLIER same-tick tokens at full precision
    where sequential ticks read them back quantized, so fused output
    would drift from plain ticks on logit ties (the exact caveat
    verify_append documents for drafts). Bit-identity outranks the
    scatter savings; the dispatch overhead fusion targets is host-side
    anyway.
    """
    cache = write_decode_all_layers(cache, k_all, v_all)
    return cache._replace(lengths=cache.lengths + inc)


def _multi_write_indices(cache: PagedKVCache,
                         S: int) -> tuple[jax.Array, jax.Array]:
    """(phys, slot) [B,S] for S consecutive candidate positions per row.
    Positions past the table's width go to garbage page 0 — clamping
    them onto the last real page would wrap their slot index into
    TRUSTED kv (observed: a fully-allocated row near its budget had
    early slots of its last page overwritten by draft positions).
    Shared by every multi-position write so the containment logic has
    exactly one copy."""
    ps = cache.page_size
    pos = cache.lengths[:, None] + jnp.arange(S)[None, :]      # [B,S]
    logical = pos // ps
    safe = jnp.minimum(logical, cache.max_pages_per_row - 1)
    phys = jnp.take_along_axis(cache.page_table, safe, axis=1)     # [B,S]
    phys = jnp.where(logical < cache.max_pages_per_row, phys, 0)
    return phys, pos % ps


def write_decode_multi_all_layers(cache: PagedKVCache, k_all: jax.Array,
                                  v_all: jax.Array) -> PagedKVCache:
    """Write S candidate slots per row for EVERY layer in one scatter —
    :func:`write_decode_all_layers`'s speculative-verify generalisation
    (and :func:`write_decode_multi`'s all-layer one). k_all/v_all:
    [L, B, S, Hkv, D]; same beyond-table garbage containment as
    write_decode_multi."""
    phys, slot = _multi_write_indices(cache, k_all.shape[2])
    return _scatter_kv(cache, k_all, v_all,
                       lambda arr, upd: arr.at[:, phys, slot].set(
                           upd, mode="drop"),
                       # update [B, S, L, Hkv] (advanced dims 1, 3 front)
                       lambda arr, upd: arr.at[:, phys, :, slot].set(
                           upd.transpose(1, 2, 0, 3), mode="drop"))


def write_decode_multi(cache: PagedKVCache, layer: jax.Array, k: jax.Array,
                       v: jax.Array) -> PagedKVCache:
    """Write S consecutive candidate slots per row for one layer — the
    speculative-verify generalisation of :func:`write_decode`.

    k/v: [B, S, Hkv, D]; row b's position j goes to page
    ``page_table[b, (lengths[b]+j) // ps]`` slot ``(lengths[b]+j) % ps``.
    Positions past the row's page allocation hit table entries that are 0
    by contract — the garbage page — so near-budget rows' untrusted draft
    writes are naturally contained (see _multi_write_indices)."""
    phys, slot = _multi_write_indices(cache, k.shape[1])
    return _scatter_kv(cache, k, v,
                       lambda arr, upd: arr.at[layer, phys, slot].set(
                           upd, mode="drop"),
                       # layer-sliced target [N, Hkv, ps]; update [B, S,
                       # Hkv] as-is (advanced dims 0, 2 -> front)
                       lambda arr, upd: arr.at[layer, phys, :, slot].set(
                           upd, mode="drop"))


def copy_slot(cache: PagedKVCache, src_pos: jax.Array,
              dst_pos: jax.Array) -> PagedKVCache:
    """Move ONE kv slot per row (all layers) from absolute position
    ``src_pos[b]`` to ``dst_pos[b]`` — the tree-speculation sibling
    compaction (serve/scheduler.py tree spec tick): an accepted sibling
    leaf's kv, written at its node slot, is copied onto the accepted-
    path slot before lengths advance over it. Raw pool words move
    (int8 values + their head-major scales together), so the copy is
    exact — never a requantize. Rows with ``src_pos == dst_pos``
    self-copy harmlessly; positions past a row's table width route to
    garbage page 0 both ways (same containment as
    :func:`_multi_write_indices`).
    """
    ps = cache.page_size

    def indices(pos):                                  # [B] -> (phys, slot)
        logical = pos // ps
        safe = jnp.minimum(logical, cache.max_pages_per_row - 1)
        phys = jnp.take_along_axis(cache.page_table, safe[:, None],
                                   axis=1)[:, 0]
        phys = jnp.where(logical < cache.max_pages_per_row, phys, 0)
        return phys.astype(jnp.int32), (pos % ps).astype(jnp.int32)

    sp, so = indices(src_pos)
    dp, do = indices(dst_pos)
    out = cache._replace(
        k=cache.k.at[:, dp, do].set(cache.k[:, sp, so]),
        v=cache.v.at[:, dp, do].set(cache.v[:, sp, so]))
    if cache.quantized:
        # Head-major scales [L,N,Hkv,ps_pad]: the batch indices sit on
        # non-adjacent dims, so index every axis explicitly to keep the
        # gather/scatter in [L,B,Hkv] array order.
        L, _, Hkv, _ = cache.k_scale.shape
        li = jnp.arange(L)[:, None, None]
        hi = jnp.arange(Hkv)[None, None, :]
        src_ix = (li, sp[None, :, None], hi, so[None, :, None])
        dst_ix = (li, dp[None, :, None], hi, do[None, :, None])
        out = out._replace(
            k_scale=cache.k_scale.at[dst_ix].set(cache.k_scale[src_ix]),
            v_scale=cache.v_scale.at[dst_ix].set(cache.v_scale[src_ix]))
    return out


# -- page-set extract / inject (KV tiering, serve/kv_tier.py) -----------------

def gather_pages(cache: PagedKVCache, pages: jax.Array) -> tuple:
    """Pull a page set's content out of the pool in ONE gather per array
    — the device half of parking a session's KV to host RAM (the caller
    jits this, reads the result back with a single sync, and frees the
    physical pages).

    pages: [P] physical page ids (pad with 0 — the garbage page — to a
    power-of-two bucket so the compile cache stays small; padded lanes
    carry garbage the caller ignores). Returns (k [L,P,ps,Hkv,D],
    v [L,P,ps,Hkv,D], k_scale, v_scale) with the scale pair None for
    bf16 pools and the head-major [L,P,Hkv,ps_pad] storage layout for
    int8 — the raw pool bits, NOT a dequant: park/wake must round-trip
    the exact int8+scale words so a resumed session attends bit-identical
    KV to one that never left HBM.
    """
    k = cache.k[:, pages]
    v = cache.v[:, pages]
    if not cache.quantized:
        return k, v, None, None
    return k, v, cache.k_scale[:, pages], cache.v_scale[:, pages]


def scatter_pages(cache: PagedKVCache, pages: jax.Array, k: jax.Array,
                  v: jax.Array, k_scale: Optional[jax.Array] = None,
                  v_scale: Optional[jax.Array] = None) -> PagedKVCache:
    """Land a parked page set back into the pool in ONE scatter per
    array — the device half of waking a session from host RAM. Inverse
    of :func:`gather_pages`: the payload is raw pool words (int8 +
    head-major scales included), so wake is a copy, never a requantize.

    pages: [P] freshly-allocated physical ids, padded with 0 to the
    payload's bucket — duplicate 0 entries scatter garbage into the
    garbage page, which holds garbage by contract. The caller installs
    the waking row's table/lengths separately (atomically with its
    suffix prefill — the chunked-admission splice discipline); this
    touches pool content only.
    """
    cache = cache._replace(k=cache.k.at[:, pages].set(k),
                           v=cache.v.at[:, pages].set(v))
    if k_scale is not None:        # payload structure — static under jit
        cache = cache._replace(
            k_scale=cache.k_scale.at[:, pages].set(k_scale),
            v_scale=cache.v_scale.at[:, pages].set(v_scale))
    return cache


def set_row_table(cache: PagedKVCache, row: int | jax.Array,
                  pages: jax.Array) -> PagedKVCache:
    """Install a row's page map (host-allocated physical ids, padded with
    0s to max_pages_per_row) and reset its length to 0."""
    table = cache.page_table.at[row].set(pages.astype(jnp.int32))
    return cache._replace(page_table=table,
                          lengths=cache.lengths.at[row].set(0))


def gather_dense(cache: PagedKVCache, layer: int, max_seq: int,
                 ) -> tuple[jax.Array, jax.Array]:
    """Materialise one layer back to dense [B, max_seq, Hkv, D] (test
    oracle / debugging only — defeats the point in production). Returns
    the POOL dtype for bf16 pools and float32 (full-precision dequant)
    for quantized pools — callers mixing it with bf16 tensors must cast
    explicitly; the f32 return is deliberate so oracles compare at the
    dequant's native precision."""
    ps = cache.page_size
    pos = jnp.arange(max_seq)
    logical = pos // ps                                # [max_seq]
    B = cache.page_table.shape[0]
    phys = cache.page_table[:, logical]                # [B, max_seq]
    slot = jnp.broadcast_to(pos % ps, (B, max_seq))
    k = cache.k[layer][phys, slot]                     # [B, max_seq, Hkv, D]
    v = cache.v[layer][phys, slot]
    if cache.quantized:
        k = (k.astype(jnp.float32)
             * cache.k_scale_view[layer][phys, slot][..., None])
        v = (v.astype(jnp.float32)
             * cache.v_scale_view[layer][phys, slot][..., None])
    return k, v
