"""Pallas flash-decode attention over the paged KV pool.

The decode-attention kernel named by the north star (BASELINE.json; the
reference has no kernels at all — its attention lives inside Ollama,
web/streamlit_app.py:91). One query token per batch row attends to that
row's live context through its page table.

Kernel shape (TPU-first):
- grid ``(B, Hkv, P)`` — one program per (row, kv-head, page), pages
  innermost so the output block is revisited and accumulation state stays
  resident in VMEM scratch across the page walk.
- the page pool stays in HBM (``pl.ANY``); each program's ``[page_size, D]``
  k/v tiles are DMA'd by the BlockSpec pipeline using **scalar-prefetched
  page-table indices** — the fetch address is data-dependent (that is the
  whole point of paging) but known before the program body runs, so Mosaic
  double-buffers page fetches exactly like a dense pipeline.
- online softmax (flash accumulation) in f32: running max ``m``, running
  sum ``l``, unnormalised accumulator ``acc`` live in VMEM scratch; the
  GQA group's ``rep`` query heads ride the sublane dim so the per-page
  score matmul ``[rep, D] x [D, page_size]`` lands on the MXU.
- dead pages (beyond the row's length) are skipped with ``pl.when``; their
  table entries point at garbage page 0 (ops/paged_kv.py), so the
  pipeline's fetch stays in bounds.

``interpret=True`` runs the same kernel on CPU for hardware-free tests
(SURVEY.md §4); :func:`paged_attention_reference` is the jnp oracle.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _kernel(pt_ref, len_ref, layer_ref, q_ref, k_ref, v_ref, o_ref,
            m_ref, l_ref, acc_ref, *, page_size: int, scale: float):
    b = pl.program_id(0)
    p = pl.program_id(2)
    num_p = pl.num_programs(2)

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    length = len_ref[b]
    page_start = p * page_size

    @pl.when(page_start < length)
    def _accumulate():
        q = q_ref[0, 0].astype(jnp.float32)            # [rep, D]
        k = k_ref[0, 0, 0].astype(jnp.float32)         # [page_size, D]
        v = v_ref[0, 0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(                       # [rep, page_size]
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        pos = page_start + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, dimension=1)
        s = jnp.where(pos < length, s, NEG_INF)

        m_prev = m_ref[:, :1]                          # [rep, 1]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_cur)
        probs = jnp.exp(s - m_cur)                     # [rep, page_size]
        l_ref[:, :1] = l_ref[:, :1] * alpha + jnp.sum(probs, -1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jnp.dot(
            probs, v, preferred_element_type=jnp.float32)
        m_ref[:, :1] = m_cur

    @pl.when(p == num_p - 1)
    def _finalise():
        # length >= 1 by the serving contract (the slot just written is
        # always attended), so l > 0.
        o_ref[0, 0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("pages", "interpret"))
def paged_attention(q: jax.Array, k_pages: jax.Array, v_pages: jax.Array,
                    page_table: jax.Array, lengths: jax.Array,
                    layer: jax.Array, *, pages: int,
                    interpret: bool = False) -> jax.Array:
    """Decode attention for one layer over the paged pool.

    q: [B, Hq, D] (one token per row); k_pages/v_pages: the full pool
    [L, N, Hkv, page_size, D] (stays in HBM — ``layer`` selects inside the
    index map, so no layer copy is materialised); page_table: [B, >=pages];
    lengths: [B] tokens to attend per row (including the slot this step
    wrote — callers pass ``cache.lengths + 1``); layer: scalar int32;
    pages: static page-walk count (the serving window ladder:
    ``ceil(window / page_size)``). Returns [B, Hq, D] in q.dtype.
    """
    B, Hq, D = q.shape
    L, N, Hkv, page_size, _ = k_pages.shape
    rep = Hq // Hkv
    scale = 1.0 / (D ** 0.5)
    pt = page_table[:, :pages].astype(jnp.int32)
    layer = jnp.asarray(layer, jnp.int32).reshape(1)

    # q laid out [B, Hkv, rep, D] so each program's block (1, 1, rep, D) is
    # EQUAL to the array's trailing dims — Mosaic requires trailing block
    # dims divisible by (8, 128) *or* equal to the full dims, and rep is
    # small (llama3.1: 4; tiny: 2), so equality is the only layout that
    # lowers on real TPUs.
    q4 = q.reshape(B, Hkv, rep, D)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,       # page_table, lengths, layer
        grid=(B, Hkv, pages),
        in_specs=[
            pl.BlockSpec((1, 1, rep, D),
                         lambda b, h, p, pt, ln, ly: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, 1, page_size, D),
                         lambda b, h, p, pt, ln, ly: (ly[0], pt[b, p], h, 0, 0)),
            pl.BlockSpec((1, 1, 1, page_size, D),
                         lambda b, h, p, pt, ln, ly: (ly[0], pt[b, p], h, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, rep, D),
                               lambda b, h, p, pt, ln, ly: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rep, 128), jnp.float32),   # running max m
            pltpu.VMEM((rep, 128), jnp.float32),   # running sum l
            pltpu.VMEM((rep, D), jnp.float32),     # unnormalised acc
        ],
    )
    out = pl.pallas_call(
        functools.partial(_kernel, page_size=page_size, scale=scale),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, rep, D), q.dtype),
        interpret=interpret,
    )(pt, lengths.astype(jnp.int32), layer, q4, k_pages, v_pages)
    return out.reshape(B, Hq, D)


def paged_attention_reference(q: jax.Array, k_pages: jax.Array,
                              v_pages: jax.Array, page_table: jax.Array,
                              lengths: jax.Array, layer,
                              *, pages: int) -> jax.Array:
    """jnp oracle: gather the pages dense, run masked GQA attention
    (models/layers.attend_gqa). Same signature/semantics as the kernel."""
    from ..models.layers import attend_gqa

    B = q.shape[0]
    page_size = k_pages.shape[3]
    window = pages * page_size
    pos = jnp.arange(window)
    phys = page_table[:, :pages][:, pos // page_size]      # [B, window]
    slot = jnp.broadcast_to(pos % page_size, (B, window))
    k = k_pages[layer][phys, :, slot]                      # [B, window, Hkv, D]
    v = v_pages[layer][phys, :, slot]
    mask = (pos[None, :] < lengths[:, None])[:, None, None, :]  # [B,1,1,W]
    return attend_gqa(q[:, None], k, v, mask)[:, 0]        # [B, Hq, D]
