"""Decode attention over the paged KV pool — gather path + Pallas kernel.

The decode-attention op named by the north star (BASELINE.json; the
reference has no kernels at all — its attention lives inside Ollama,
web/streamlit_app.py:91). One query token per batch row attends to that
row's live context through its page table. Two interchangeable
implementations, both pinned to the same oracle (tests/test_ops_paged.py):

- ``impl="gather"`` (default): gather each row's pages as whole
  contiguous ``[page_size, Hkv, D]`` blocks (B x pages block reads — the
  token-major pool layout makes the result a pure reshape, no
  transpose), then run the fused dense GQA attend. XLA fuses the mask/
  softmax chain, and the gathered window is the same bytes a dense cache
  would read. Pure-XLA, so it is also the fast path for CPU tests.
- ``impl="kernel"``: a Pallas flash-decode kernel, grid ``(B, pages)``,
  each program DMA-ing one whole page (``[page_size, Hkv, D]`` — full
  trailing block dims, the layout Mosaic lowers without relayouts) via
  scalar-prefetched page-table indices, accumulating online-softmax
  state in VMEM scratch across the page walk.

Measured on a v5e chip at serving shapes (B=32, bench-1b, W=192): the
gather path wins and is the default everywhere. Two history lessons,
for the record. (1) The first kernel used grid ``(B, Hkv, pages)`` over
a head-major pool layout — 8x more programs, each fetching a strided
``[page_size, D]`` tile — and per-program overhead made the full step
227 ms: few big blocks beat many small ones. (2) Round 4 rebuilt the
append path as a Pallas kernel three ways (manual page DMAs; gathered
windows with per-head dots; gathered windows with GQA-as-selection-
matmuls) and every variant lost to XLA's gather + fused VPU math — see
_append_kernel's docstring for the numbers. The durable round-4 wins
were XLA-side instead: joint (layer, page) indexing so the gather reads
only the window (not a materialised layer slice), and head-major
lane-padded scale storage so the scale arrays stop layout-thrashing in
the decode carry (together ~0.7 ms off a 3.9 ms step).

``PAGED_ATTN_IMPL`` selects the process-wide default; ``interpret=True``
runs the kernel on CPU for hardware-free tests (SURVEY.md §4);
:func:`paged_attention_reference` is the jnp oracle.

Round-5 closure of the short-window kernel question (the round-4
verdict's "(B x Hkv)-grid with rep folded into the dot"): the shape is
settled by launch arithmetic derived from the kernels already measured
here. Attention must run inside the per-layer scan (layer i+1's q
depends on layer i's output), so ANY kernel pays 22 launches per step;
the flash kernel's measured overhead is ~1 us per program (32 programs
x 22 calls = 704 programs, 1.4 ms total vs its 0.7 ms byte bound). A
(B x Hkv) grid is B*Hkv = 256 programs x 22 calls = 5,632 programs
~= 5.6 ms of program overhead alone — 2x the ENTIRE 2.97 ms step. The
gather path's only waste is the materialise round trip of the bf16
window (~0.5 ms/step at W=192), strictly smaller than any per-program
overhead a Pallas grid can reach at these shapes. The calculus flips
at long windows, where the materialise waste grows linearly with W
(~33 ms of the 40 ms step at W=4096) and per-program overhead does
not — which is why the flash-APPEND kernel below owns that regime.

Round-8 closure of the long-window regime (the round-5 verdict's
top-ranked item): the round-5 flash-append kernel was pinned to the
single-chunk band by a VMEM stack OOM — double-buffered WHOLE-CHUNK
scratch plus whole-chunk bf16 dequant copies (20.7 MB measured at
2048-token chunks) — so W > 2048 fell back to the gather path and its
linear materialise waste (40.2 ms at W=4096 int8 B=32, 5.5x the ~7 ms
byte bound). Two restructurings were prototyped, both holding TILES in
VMEM instead of whole windows:

- **(B, chunk) grid with cross-chunk online-softmax merge in VMEM
  scratch accumulators** (split-K / flash-decoding shape, Dao et al.;
  the paged pool walk is vLLM PagedAttention's): each program folds one
  bounded chunk (1024 int8 / 512 bf16 tokens, 8.2 MB VMEM ceiling
  including the double-buffered DMA slots and the chunk-local dequant
  view) into (m, l, acc) scratch that persists across the chunk axis of
  the grid; the next chunk's page DMAs issue before the current chunk's
  compute, crossing row boundaries, so launch overhead amortises across
  the grid instead of a kernel-internal chunk loop. **KEPT — the
  winner**: W=4096 int8 B=32 measures 11.6-12.4 ms per step
  (3.2-3.5x the gather path, 1.7x the byte bound) and W=8192 measures
  21.8 ms, both page sizes within the session spread.
- per-tile int8 dequant inside the softmax loop of the old (B,) grid
  (the chunk stays int8 in VMEM; each [128, HD] tile converts in
  registers as it feeds the MXU, so the whole-chunk bf16 copy never
  exists). **DROPPED — the loser, recorded here**: the VMEM ceiling
  clears (9.1 MB at 2048-token chunks) but the kernel-internal chunk
  loop serialises DMA waits against the tile loop — W=4096 int8 B=32
  measured 24.9 ms (2.1x the grid form) and the tile-granular
  dequant added ~8% VPU time at W=2048 where the two shapes otherwise
  tie.

The grid kernel is now the DEFAULT dispatch for decode append at
W >= ``PAGED_APPEND_FLASH_MIN_W`` (2048) on TPU; the gather path stays
default below it and everywhere on CPU (non-interpret ``pallas_call``
needs the hardware). See ``_flash_append_policy`` for the exact rule
and docs/serving.md ("long-window kernel") for the dispatch table and
measured ladder.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils.env import env_int, env_or

NEG_INF = -1e30

_DEFAULT_IMPL = env_or("PAGED_ATTN_IMPL", "gather")


def _kernel(pt_ref, len_ref, layer_ref, q_ref, k_ref, v_ref, o_ref,
            m_ref, l_ref, acc_ref, *, page_size: int, rep: int,
            scale: float):
    b = pl.program_id(0)
    p = pl.program_id(1)
    num_p = pl.num_programs(1)

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    length = len_ref[b]
    page_start = p * page_size

    @pl.when(page_start < length)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32)               # [Hq, D]
        kpage = k_ref[0, 0].astype(jnp.float32)        # [ps, Hkv, D]
        vpage = v_ref[0, 0].astype(jnp.float32)
        Hkv = kpage.shape[1]
        for h in range(Hkv):                           # static unroll
            sl = slice(h * rep, (h + 1) * rep)
            s = jax.lax.dot_general(                   # [rep, ps]
                q[sl], kpage[:, h], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            pos = page_start + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, dimension=1)
            s = jnp.where(pos < length, s, NEG_INF)

            m_prev = m_ref[sl, :1]                     # [rep, 1]
            m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            alpha = jnp.exp(m_prev - m_cur)
            probs = jnp.exp(s - m_cur)                 # [rep, ps]
            l_ref[sl, :1] = l_ref[sl, :1] * alpha + jnp.sum(
                probs, -1, keepdims=True)
            acc_ref[sl, :] = acc_ref[sl, :] * alpha + jnp.dot(
                probs, vpage[:, h], preferred_element_type=jnp.float32)
            m_ref[sl, :1] = m_cur

    @pl.when(p == num_p - 1)
    def _finalise():
        # length >= 1 by the serving contract (the slot just written is
        # always attended), so l > 0.
        o_ref[0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("pages", "interpret"))
def _paged_attention_kernel(q, k_pages, v_pages, page_table, lengths, layer,
                            *, pages: int, interpret: bool = False):
    B, Hq, D = q.shape
    L, N, page_size, Hkv, _ = k_pages.shape
    rep = Hq // Hkv
    scale = 1.0 / (D ** 0.5)
    pt = page_table[:, :pages].astype(jnp.int32)
    layer = jnp.asarray(layer, jnp.int32).reshape(1)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,       # page_table, lengths, layer
        grid=(B, pages),
        in_specs=[
            pl.BlockSpec((1, Hq, D), lambda b, p, pt, ln, ly: (b, 0, 0)),
            # One whole page per program: full trailing dims, fetched at
            # the scalar-prefetched (layer, physical page) address.
            pl.BlockSpec((1, 1, page_size, Hkv, D),
                         lambda b, p, pt, ln, ly: (ly[0], pt[b, p], 0, 0, 0)),
            pl.BlockSpec((1, 1, page_size, Hkv, D),
                         lambda b, p, pt, ln, ly: (ly[0], pt[b, p], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, Hq, D), lambda b, p, pt, ln, ly: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Hq, 128), jnp.float32),    # running max m
            pltpu.VMEM((Hq, 128), jnp.float32),    # running sum l
            pltpu.VMEM((Hq, D), jnp.float32),      # unnormalised acc
        ],
    )
    return pl.pallas_call(
        functools.partial(_kernel, page_size=page_size, rep=rep, scale=scale),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        interpret=interpret,
    )(pt, lengths.astype(jnp.int32), layer, q, k_pages, v_pages)


def _paged_attention_gather(q, k_pages, v_pages, page_table, lengths, layer,
                            *, pages: int):
    """Whole-page block gather + fused dense GQA attend (see module
    docstring for why this wins at decode shapes)."""
    from ..models.layers import attend_gqa

    B = q.shape[0]
    L, N, ps, Hkv, D = k_pages.shape
    W = pages * ps
    # Joint (layer, page) index into the flat [L*N] page axis: slicing the
    # layer first (k_pages[layer][pt]) materialises the layer's ENTIRE
    # pool slice before the gather — measured at ~0.4 ms/step of pure
    # copy at bench serving shapes. One gather from the flat pool reads
    # only the window's pages.
    pt = layer * N + page_table[:, :pages].astype(jnp.int32)
    k = k_pages.reshape(L * N, ps, Hkv, D)[pt].reshape(B, W, Hkv, D)
    v = v_pages.reshape(L * N, ps, Hkv, D)[pt].reshape(B, W, Hkv, D)
    mask = (jnp.arange(W)[None, :] < lengths[:, None])[:, None, None, :]
    return attend_gqa(q[:, None], k, v, mask)[:, 0]


def _gqa_selection_matrices(Hq: int, Hkv: int, D: int, rep: int):
    """Constant 0/1 selection matrices built from in-register iotas
    (shared by _append_kernel and the flash-append kernel): SEL tiles /
    collapses per-head D-blocks, BLOCKM masks q columns to their own kv
    block (built both ways — Mosaic cannot transpose i1), EXPT expands
    kv-head rows to query-head columns. Returns
    (sel bf16 [HD, D], blockm bool [HD, Hq], blockm_t bool [Hq, HD],
    expt f32 [Hq, Hkv])."""
    HD = Hkv * D
    cmod = jax.lax.broadcasted_iota(jnp.int32, (HD, D), 0) % D
    drng = jax.lax.broadcasted_iota(jnp.int32, (HD, D), 1)
    sel = (cmod == drng).astype(jnp.bfloat16)
    cdiv = jax.lax.broadcasted_iota(jnp.int32, (HD, Hq), 0) // D
    hdiv = jax.lax.broadcasted_iota(jnp.int32, (HD, Hq), 1) // rep
    blockm = cdiv == hdiv
    cdiv2 = jax.lax.broadcasted_iota(jnp.int32, (Hq, HD), 1) // D
    hdiv2 = jax.lax.broadcasted_iota(jnp.int32, (Hq, HD), 0) // rep
    blockm_t = cdiv2 == hdiv2
    hh = jax.lax.broadcasted_iota(jnp.int32, (Hq, Hkv), 0) // rep
    gg = jax.lax.broadcasted_iota(jnp.int32, (Hq, Hkv), 1)
    expt = (hh == gg).astype(jnp.float32)
    return sel, blockm, blockm_t, expt


def _append_kernel(len_ref, q_ref, kc_ref, vc_ref, kwin_ref, vwin_ref,
                   skw_ref, svw_ref, o_ref, *, page_size: int,
                   pages: int, rep: int, rows: int, scale: float,
                   quantized: bool):
    """Append-attention over GATHERED windows, one program per
    ``rows``-row block.

    Division of labour, settled by measurement: XLA's native gather
    fetches each row's pages from the paged pool (its scattered-page
    DMA machinery runs at ~1 TB/s effective; a manual per-page
    ``make_async_copy`` loop in an earlier version of this kernel spent
    ~280 us/layer-call on DMA-descriptor issue alone), and this kernel
    consumes the gathered windows as auto-pipelined VMEM blocks and
    replaces what XLA did WORSE: the rep(=2)-row GQA attention math that
    lowered onto the VPU with layout copies around the scale arrays
    (~0.8 ms of a 3.0 ms bench-1b step).

    Constant 0/1 selection matrices (built in-register from iotas) turn
    every GQA shuffle into an MXU dot: ONE [W, HD] x [HD, Hq] score dot
    and one [Hq, W] x [W, HD] output dot per row, with the kv-head ->
    query-head expansion and the output block-collapse as tiny constant
    matmuls. All big dots take bf16 inputs with f32 accumulation — the
    same precision contract as the gather path's attend_gqa. The current
    token's (k, v) folds in as one extra softmax term, so pool writes
    batch AFTER the layer scan (write_decode_all_layers).
    """
    W = pages * page_size
    Hkv = kc_ref.shape[1]
    Hq = rep * Hkv
    D = kc_ref.shape[2]
    HD = Hkv * D
    pos_col = jax.lax.broadcasted_iota(jnp.int32, (W, 1), dimension=0)
    sel, blockm, blockm_t, expt = _gqa_selection_matrices(Hq, Hkv, D, rep)
    expt = expt.astype(jnp.bfloat16)

    g0 = pl.program_id(0)
    for r in range(rows):
        length = len_ref[g0 * rows + r]
        q_r = q_ref[r].astype(jnp.bfloat16)                     # [Hq, D]
        valid_col = pos_col < length                            # [W, 1]
        kflat = kwin_ref[r].reshape(W, HD).astype(jnp.bfloat16)
        vflat = vwin_ref[r].reshape(W, HD).astype(jnp.bfloat16)

        # Q stacked into its kv block: [HD, Hq] = tile q columns via SEL,
        # zero the off-block copies.
        q_cols = jax.lax.dot(sel, q_r.T,
                             preferred_element_type=jnp.float32)
        q_blk = jnp.where(blockm, q_cols.astype(jnp.bfloat16),
                          jnp.zeros((), jnp.bfloat16))          # [HD, Hq]
        s = jax.lax.dot(kflat, q_blk,
                        preferred_element_type=jnp.float32) * scale
        if quantized:
            sk_all = jnp.concatenate(
                [skw_ref[r, p][:, :page_size] for p in range(pages)],
                axis=1)                                         # [Hkv, W]
            sv_all = jnp.concatenate(
                [svw_ref[r, p][:, :page_size] for p in range(pages)],
                axis=1)
            skw = jax.lax.dot(sk_all.T, expt.T.astype(jnp.float32),
                              preferred_element_type=jnp.float32)
            s = s * skw                                         # [W, Hq]
        s = jnp.where(valid_col, s, NEG_INF)

        # Current token's k/v, expanded kv-head -> query-head via EXPT.
        kcur = jax.lax.dot(expt, kc_ref[r].astype(jnp.bfloat16),
                           preferred_element_type=jnp.float32)  # [Hq, D]
        vcur = jax.lax.dot(expt, vc_ref[r].astype(jnp.bfloat16),
                           preferred_element_type=jnp.float32)
        s_cur = jnp.sum(q_r.astype(jnp.float32) * kcur, axis=-1,
                        keepdims=True).T * scale

        m = jnp.maximum(jnp.max(s, 0, keepdims=True), s_cur)    # [1, Hq]
        p_w = jnp.exp(s - m)                                    # [W, Hq]
        p_c = jnp.exp(s_cur - m)                                # [1, Hq]
        den = jnp.sum(p_w, 0, keepdims=True) + p_c              # [1, Hq]
        if quantized:
            svw = jax.lax.dot(sv_all.T, expt.T.astype(jnp.float32),
                              preferred_element_type=jnp.float32)
            p_w = p_w * svw
        out_full = jax.lax.dot(p_w.T.astype(jnp.bfloat16), vflat,
                               preferred_element_type=jnp.float32)
        out_full = jnp.where(blockm_t, out_full, 0.0)           # [Hq, HD]
        out = jax.lax.dot(out_full.astype(jnp.bfloat16), sel,
                          preferred_element_type=jnp.float32)   # [Hq, D]
        out = (out + p_c.T * vcur) / den.T
        o_ref[r] = out.astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("pages", "interpret", "quantized"))
def _paged_append_kernel_call(q, k_cur, v_cur, k_pages, v_pages, k_scale,
                              v_scale, page_table, lengths, layer, *,
                              pages: int, quantized: bool,
                              interpret: bool = False):
    B, Hq, D = q.shape
    L, N, page_size, Hkv, _ = k_pages.shape
    rep = Hq // Hkv
    scale = 1.0 / (D ** 0.5)
    W = pages * page_size
    # XLA joint-index gather fetches the windows (see _append_kernel for
    # why this beats in-kernel page DMAs).
    pt = layer * N + page_table[:, :pages].astype(jnp.int32)
    kwin = k_pages.reshape(L * N, page_size, Hkv, D)[pt].reshape(
        B, W, Hkv, D)
    vwin = v_pages.reshape(L * N, page_size, Hkv, D)[pt].reshape(
        B, W, Hkv, D)
    if quantized:
        ps_pad = k_scale.shape[-1]
        skw = k_scale.reshape(L * N, Hkv, ps_pad)[pt]   # [B, P, Hkv, pad]
        svw = v_scale.reshape(L * N, Hkv, ps_pad)[pt]
    else:
        ps_pad = 128
        skw = jnp.zeros((B, pages, Hkv, ps_pad), jnp.float32)
        svw = skw

    # Rows per program bounded by the window VMEM footprint (k+v blocks
    # + f32 scales, double-buffered by Mosaic); target ~4 MB.
    bytes_per_row = 2 * W * Hkv * D * k_pages.dtype.itemsize
    if quantized:
        bytes_per_row += 2 * pages * Hkv * ps_pad * 4
    rows = max(1, min(B, (4 * 1024 * 1024) // max(1, bytes_per_row)))
    while B % rows:
        rows -= 1

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,       # lengths (SMEM scalars)
        grid=(B // rows,),
        in_specs=[
            pl.BlockSpec((rows, Hq, D), lambda i, ln: (i, 0, 0)),
            pl.BlockSpec((rows, Hkv, D), lambda i, ln: (i, 0, 0)),
            pl.BlockSpec((rows, Hkv, D), lambda i, ln: (i, 0, 0)),
            pl.BlockSpec((rows, W, Hkv, D), lambda i, ln: (i, 0, 0, 0)),
            pl.BlockSpec((rows, W, Hkv, D), lambda i, ln: (i, 0, 0, 0)),
            pl.BlockSpec((rows, pages, Hkv, ps_pad),
                         lambda i, ln: (i, 0, 0, 0)),
            pl.BlockSpec((rows, pages, Hkv, ps_pad),
                         lambda i, ln: (i, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((rows, Hq, D), lambda i, ln: (i, 0, 0)),
    )
    out = pl.pallas_call(
        functools.partial(_append_kernel, page_size=page_size, pages=pages,
                          rep=rep, rows=rows, scale=scale,
                          quantized=quantized),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        interpret=interpret,
    )(lengths.astype(jnp.int32), q, k_cur, v_cur, kwin, vwin, skw, svw)
    return out


# Decode append-attention implementation default at SHORT windows.
# "gather" (XLA) wins at serving shapes and stays the default there; the
# Pallas block kernel (PAGED_APPEND_IMPL=kernel) is kept for the record.
# Measured on v5e, bench-1b B=32 W=192, per step: XLA gather+attend
# ~1.0 ms; manual-DMA kernel ~6.2 ms in DMA-descriptor issue alone (384
# page copies); the gather-fed block kernel ~1.8 ms (the GQA-via-
# selection-matmul form spends 8x the MXU passes; per-head dots relayout
# instead). At rep=2 decode GQA, XLA's fused VPU math is simply the
# better tool — until the window is long enough that the gather's
# materialise copy dominates, where the multi-chunk flash-append kernel
# takes over by default (see _flash_append_policy).
_APPEND_IMPL = env_or("PAGED_APPEND_IMPL", "gather")


def _append_kernel_wanted() -> bool:
    return _APPEND_IMPL == "kernel"


def paged_attention_append(q, k_cur, v_cur, cache, lengths, layer,
                           *, pages: int, interpret: bool = False):
    """Decode attention where this step's k/v is NOT yet in the pool:
    attend over the pool window (positions < ``lengths``) and merge the
    current token's own (k_cur, v_cur) contribution with one exact
    online-softmax step.

    Why: writing each layer's k/v into the pool BEFORE attending forces
    one [B]-indexed pool scatter per layer inside the decode scan — 22+
    small scatters per step whose fixed cost is measurable against the
    bandwidth bound. With the merge, the scan collects per-layer k/v as
    stacked outputs and ONE batched scatter (ops/paged_kv.
    write_decode_all_layers) lands the whole step after the trunk.
    On bf16 pools results are identical to write-then-attend (same f32
    softmax over the same set; pinned by tests/test_ops_paged.py). On
    int8 pools the CURRENT token is attended at FULL precision here,
    where write-then-attend would read it back quantized — a
    sub-quantisation-noise difference that can flip logit ties (the
    same caveat verify_append documents for drafts; see the scheduler's
    kv_quant notes).

    q/k_cur/v_cur: [B, Hq|Hkv, D] (one token per row); cache: the
    PagedKVCache (bf16 or int8 pools); lengths: positions already in
    the pool per row (NOT including the current token). Returns
    [B, Hq, D] in q.dtype.

    The XLA gather+merge below is the DEFAULT at short windows and
    everywhere on CPU (it measured fastest at short serving windows —
    see the module docstring's round-4 history). At windows >=
    ``PAGED_APPEND_FLASH_MIN_W`` (default 2048) on TPU the multi-chunk
    flash-append kernel (_paged_attention_flash_append) is the default
    instead: one HBM pass over the pages, no gathered-window
    materialisation — the round-8 long-window win. Overrides:
    ``PAGED_APPEND_IMPL=kernel`` pins the round-4 gathered-window block
    kernel (_append_kernel); ``PAGED_APPEND_IMPL=flash`` pins the flash
    kernel at every window; ``PAGED_APPEND_FLASH_MIN_W=0`` disables the
    flash default (gather everywhere). See _flash_append_policy for the
    exact rule. All paths compute the same f32 softmax over the same
    score set.
    """
    B, Hq, D = q.shape
    Hkv = k_cur.shape[1]
    rep = Hq // Hkv
    if _append_kernel_wanted():
        return _paged_append_kernel_call(
            q, k_cur, v_cur, cache.k, cache.v, cache.k_scale,
            cache.v_scale, cache.page_table, lengths, layer, pages=pages,
            quantized=cache.k_scale is not None, interpret=interpret)
    W = pages * cache.k.shape[2]
    if not interpret and _flash_append_wanted(
            W, cache.k.shape[3] * cache.k.shape[4]):
        # Long-window default (round-8): the (B, chunk)-grid flash
        # kernel reads each page exactly once per (layer, step) and
        # holds only bounded tiles in VMEM, so there is no multi-chunk
        # regime restriction any more. Explicit interpret=True callers
        # (CPU tests) drive the kernel directly.
        return _paged_attention_flash_append(
            q, k_cur, v_cur, cache.k, cache.v, cache.k_scale,
            cache.v_scale, cache.page_table, lengths, layer, pages=pages,
            quantized=cache.k_scale is not None)
    scores, v, sv = _gather_window_scores(
        q[:, None], cache.k, cache.v, cache.k_scale, cache.v_scale,
        cache.page_table, lengths, layer, pages=pages)

    # Current token's own score: q . k_cur per kv head.
    qg = q.reshape(B, 1, Hkv, rep, D)
    s_cur = jnp.einsum("bgrd,bgd->bgr", qg[:, 0].astype(jnp.float32),
                       k_cur.astype(jnp.float32)) / jnp.sqrt(D).astype(
                           jnp.float32)                      # [B,G,rep]
    s_cur = s_cur[..., None, None]                           # [B,G,rep,1,1]

    m_w = jnp.max(scores, axis=-1, keepdims=True)            # [B,G,rep,1,1]
    m = jnp.maximum(m_w, s_cur)
    p = jnp.exp(scores - m)                                  # masked -> ~0
    p_cur = jnp.exp(s_cur - m)                               # > 0 always
    if sv is not None:
        pv = jnp.einsum("bgrst,btgd->bgrsd",
                        (p * sv[:, :, None, None, :]).astype(q.dtype),
                        v.astype(q.dtype)).astype(jnp.float32)
    else:
        pv = jnp.einsum("bgrst,btgd->bgrsd", p.astype(v.dtype),
                        v).astype(jnp.float32)
    num = pv + p_cur * v_cur.astype(jnp.float32)[:, :, None, None, :]
    den = jnp.sum(p, axis=-1, keepdims=True) + p_cur         # [B,G,rep,1,1]
    out = num / den
    return out[:, :, :, 0].reshape(B, Hq, D).astype(q.dtype)


def _gather_window_scores(q4, k_pages, v_pages, k_scale, v_scale,
                          page_table, lengths, layer, *, pages: int):
    """Shared preamble of the quantized gather and append paths: gather
    one layer's window, compute masked pre-softmax scores (per-position
    k scales folded in when the pool is int8), and return
    (scores [B,G,rep,S,W] f32, v [B,W,Hkv,D], sv [B,G,W] | None).
    q4: [B, S, Hq, D] (S query positions per row; every position sees the
    same window mask ``pos < lengths`` — block-internal causality is the
    caller's concern, see paged_attention_verify_append)."""
    B, S, Hq, D = q4.shape
    L, N, ps, Hkv, _ = k_pages.shape
    rep = Hq // Hkv
    W = pages * ps
    # Joint (layer, page) gather from the flat pool — no layer-slice copy
    # (see _paged_attention_gather).
    pt = layer * N + page_table[:, :pages].astype(jnp.int32)
    k = k_pages.reshape(L * N, ps, Hkv, D)[pt].reshape(B, W, Hkv, D)
    v = v_pages.reshape(L * N, ps, Hkv, D)[pt].reshape(B, W, Hkv, D)
    qg = q4.reshape(B, S, Hkv, rep, D)
    scores = jnp.einsum("bsgrd,btgd->bgrst", qg, k.astype(q4.dtype),
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(D).astype(jnp.float32)
    sv = None
    if k_scale is not None:
        # Scales are stored head-major, lane-padded [L, N, Hkv, ps_pad]
        # (paged_kv.py — the layout the append kernel DMAs); the gathered
        # [B, P, Hkv, ps] window transposes to [B, G, W] with one cheap
        # swap of small middle axes (no full-array relayout).
        ps_pad = k_scale.shape[-1]
        sk = k_scale.reshape(L * N, Hkv, ps_pad)[pt][..., :ps].transpose(
            0, 2, 1, 3).reshape(B, Hkv, W)                     # [B,G,W]
        sv = v_scale.reshape(L * N, Hkv, ps_pad)[pt][..., :ps].transpose(
            0, 2, 1, 3).reshape(B, Hkv, W)
        scores = scores * sk[:, :, None, None, :]
    mask = (jnp.arange(W)[None, :] < lengths[:, None])[:, None, None, None, :]
    return jnp.where(mask, scores, NEG_INF), v, sv


def _paged_attention_gather_quant(q, k_pages, v_pages, k_scale, v_scale,
                                  page_table, lengths, layer, *, pages: int):
    """Gather-path decode attention over an int8 pool
    (ops/paged_kv.PagedKVCache quantized=True).

    The per-(slot, kv-head) scales fold OUTSIDE the two dots: scores
    scale per kv position after the q.k contraction, and v's scale folds
    into the softmax probabilities before the p.v contraction — so the
    MXU consumes the int8 stream converted in registers, and HBM sees
    half the bf16 pool traffic (measured ~0.3 ms off a 22-layer B=32
    W=192 walk on v5e). Math mirrors models/layers.attend_gqa (f32
    scores/softmax)."""
    B, Hq, D = q.shape
    scores, v, sv = _gather_window_scores(
        q[:, None], k_pages, v_pages, k_scale, v_scale, page_table,
        lengths, layer, pages=pages)
    probs = jax.nn.softmax(scores, axis=-1)
    probs = probs * sv[:, :, None, None, :]
    out = jnp.einsum("bgrst,btgd->bsgrd", probs.astype(q.dtype),
                     v.astype(q.dtype))
    return out.reshape(B, 1, Hq, D)[:, 0]


def _flash_kernel(pt_ref, len_ref, layer_ref, q_ref, k_hbm, v_hbm, o_ref,
                  kbuf, vbuf, sems, *, page_size: int, pages: int,
                  chunk_pages: int, rep: int, scale: float):
    """One program per batch row: manually DMA that row's pages (whole
    [ps, Hkv, D] blocks, double-buffered per chunk) and fold them into an
    online-softmax accumulator carried as VALUES across a static chunk
    loop. One program per row (vs (B, pages) in ``_kernel``) keeps the
    q tile and softmax state resident and amortises program overhead —
    and unlike the gather path, HBM sees each page exactly once (the
    gather materialises a [B, W, Hkv, D] copy first: 2x the traffic of
    the bandwidth bound, measured ~1.4 ms vs the ~0.7 ms bound for a
    22-layer walk at W=192, B=32 on v5e)."""
    b = pl.program_id(0)
    ly = layer_ref[0]
    length = len_ref[b]
    num_chunks = -(-pages // chunk_pages)

    def dma(slot: int, c: int, i: int):
        page = pt_ref[b, c * chunk_pages + i]
        return (
            pltpu.make_async_copy(k_hbm.at[ly, page],
                                  kbuf.at[slot, i], sems.at[0, slot, i]),
            pltpu.make_async_copy(v_hbm.at[ly, page],
                                  vbuf.at[slot, i], sems.at[1, slot, i]),
        )

    def start_chunk(slot: int, c: int) -> None:
        for i in range(min(chunk_pages, pages - c * chunk_pages)):
            for d in dma(slot, c, i):
                d.start()

    start_chunk(0, 0)
    q = q_ref[0].astype(jnp.float32)                     # [Hq, D]
    Hq, D = q.shape
    Hkv = Hq // rep
    # Online-softmax state carried as per-kv-head VALUES across the
    # static chunk/head unrolls (Mosaic has no scatter: value-level
    # .at[].set would not lower).
    ms = [jnp.full((rep, 1), NEG_INF, jnp.float32) for _ in range(Hkv)]
    ls = [jnp.zeros((rep, 1), jnp.float32) for _ in range(Hkv)]
    accs = [jnp.zeros((rep, D), jnp.float32) for _ in range(Hkv)]

    for c in range(num_chunks):
        slot = c % 2
        if c + 1 < num_chunks:
            start_chunk((c + 1) % 2, c + 1)
        n_pages = min(chunk_pages, pages - c * chunk_pages)
        for i in range(n_pages):
            for d in dma(slot, c, i):
                d.wait()
        kc = kbuf[slot].astype(jnp.float32)       # [chunk_pages, ps, Hkv, D]
        vc = vbuf[slot].astype(jnp.float32)
        Ct = n_pages * page_size
        kc = kc[:n_pages].reshape(Ct, Hkv, D)
        vc = vc[:n_pages].reshape(Ct, Hkv, D)
        pos = c * chunk_pages * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, Ct), dimension=1)             # [1, Ct]
        valid = pos < length
        for h in range(Hkv):                             # static unroll
            sl = slice(h * rep, (h + 1) * rep)
            s = jax.lax.dot_general(                     # [rep, Ct]
                q[sl], kc[:, h], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            s = jnp.where(valid, s, NEG_INF)
            m_cur = jnp.maximum(ms[h], jnp.max(s, axis=-1, keepdims=True))
            alpha = jnp.exp(ms[h] - m_cur)
            probs = jnp.exp(s - m_cur)
            ls[h] = ls[h] * alpha + jnp.sum(probs, -1, keepdims=True)
            accs[h] = accs[h] * alpha + jnp.dot(
                probs, vc[:, h], preferred_element_type=jnp.float32)
            ms[h] = m_cur

    out = jnp.concatenate(accs, axis=0) / jnp.concatenate(ls, axis=0)
    o_ref[0] = out.astype(o_ref.dtype)


def paged_attention_verify_append(q_blk, k_blk, v_blk, cache, lengths,
                                  layer, *, pages: int, block_mask=None):
    """Speculative-verify attention where the candidate block's k/v is
    NOT yet in the pool: position j attends the pool window (positions
    < ``lengths``, identical mask for every j) plus block positions
    i <= j from the in-register k/v — one softmax over the concatenated
    score axis, so on bf16 pools results equal the write-then-attend
    ordering exactly. (On int8 pools the block is attended at FULL
    precision — unlike the old ordering, which quantized drafts before
    attending. Position 0 then sees exactly what the plain tick's
    paged_attention_append sees; positions j >= 1 view EARLIER drafts
    at full precision where the plain path, once those drafts commit,
    reads them quantized — so spec output under int8 KV tracks the
    plain engine to rounding error, not bit-exactly, at logit ties.)
    The caller lands the whole block (and all
    layers) with ONE batched scatter afterwards
    (ops/paged_kv.write_decode_multi_all_layers) — the multi-position
    generalisation of :func:`paged_attention_append`.

    q_blk: [B, S, Hq, D]; k_blk/v_blk: [B, S, Hkv, D]; lengths: pool
    positions per row (excluding the block). ``block_mask`` ([B,S,S]
    bool, True = attend, self-diagonal included) replaces the chain-
    causal triangle over the in-register block — tree speculation
    (models/llama.verify_tree_paged) passes its ancestor matrix so each
    node sees only its own root path; the pool-window mask is branch-
    agnostic either way. Returns [B, S, Hq, D].
    """
    B, S, Hq, D = q_blk.shape
    Hkv = k_blk.shape[2]
    rep = Hq // Hkv
    scores_w, v_w, sv = _gather_window_scores(
        q_blk, cache.k, cache.v, cache.k_scale, cache.v_scale,
        cache.page_table, lengths, layer, pages=pages)   # [B,G,rep,S,W]

    qg = q_blk.reshape(B, S, Hkv, rep, D)
    scores_b = jnp.einsum("bsgrd,btgd->bgrst", qg.astype(jnp.float32),
                          k_blk.astype(jnp.float32))     # [B,G,rep,S,S]
    scores_b = scores_b / jnp.sqrt(D).astype(jnp.float32)
    if block_mask is None:
        causal = (jnp.arange(S)[None, :] <= jnp.arange(S)[:, None])
        scores_b = jnp.where(causal[None, None, None], scores_b, NEG_INF)
    else:
        scores_b = jnp.where(block_mask[:, None, None], scores_b, NEG_INF)

    scores = jnp.concatenate([scores_w, scores_b], axis=-1)  # [.., W+S]
    probs = jax.nn.softmax(scores, axis=-1)
    p_w, p_b = probs[..., : scores_w.shape[-1]], probs[..., scores_w.shape[-1]:]
    if sv is not None:
        p_w = p_w * sv[:, :, None, None, :]
    out = (jnp.einsum("bgrst,btgd->bgrsd", p_w.astype(q_blk.dtype),
                      v_w.astype(q_blk.dtype)).astype(jnp.float32)
           + jnp.einsum("bgrst,btgd->bgrsd", p_b,
                        v_blk.astype(jnp.float32)))
    # [B,G,rep,S,D] -> [B,S,Hq,D]
    return out.transpose(0, 3, 1, 2, 4).reshape(B, S, Hq, D).astype(
        q_blk.dtype)


# VMEM budget for one double-buffered chunk side (k + v, bf16): chunks of
# up to 8 pages x 64 slots x Hkv x D. At bench shapes (8 heads, D=128)
# that is 1 MB per buffer side — 4 MB total with double buffering.
_FLASH_CHUNK_PAGES = 8

# Per-dtype chunk sizing for the flash-append DMA pipeline: bytes of
# one (k or v) buffer side per token AT THE CALIBRATION GEOMETRY
# (_FLASH_HD_REF) — the chunk token budget is
# _FLASH_CHUNK_TOK_BYTES * _FLASH_HD_REF / (hd * pool_itemsize), i.e.
# 1024 int8 tokens / 512 bf16 tokens / 256 f32 tokens per grid step at
# the bench-1b geometry where the budget was measured (Hkv=8, D=128,
# hd=1024), and proportionally MORE tokens per chunk at narrower KV
# geometries (bench-moe's Hkv=4: 2048 int8 tokens — same VMEM bytes,
# half the grid programs, which is half the per-chunk fixed cost the
# round-5 MoE paged-walk gap is made of). VMEM ceiling is
# geometry-invariant by construction: double-buffered int8 k+v DMA
# slots 4 MB + the chunk-local bf16 dequant view 4 MB + f32 softmax
# state ~0.2 MB = 8.2 MB, comfortably under the 16 MB stack that the
# round-5 whole-chunk design overflowed (20.7 MB). Module-level so
# tests can shrink both knobs to exercise many-chunk grids in
# interpret mode at tiny geometries.
_FLASH_CHUNK_TOK_BYTES = 1024

# The Hkv * head_dim the chunk budget and the round-8 min-W boundary
# were calibrated at (bench-1b / llama-8B class: 8 kv heads x 128).
_FLASH_HD_REF = 1024

# Floor for the geometry-scaled engagement boundary: below ~2 default
# chunks the split-K grid cannot pipeline DMAs across programs and the
# gather path's XLA fusion wins on every geometry measured.
_FLASH_MIN_W_FLOOR = 256


def _flash_append_min_w() -> int:
    """Engage the flash append kernel at windows >= this many tokens
    AT THE CALIBRATION GEOMETRY (see _flash_append_policy for the
    per-geometry scaling; TPU only; <=0 disables it and the gather path
    runs everywhere). Read per dispatch decision — NOT frozen at import
    — so tests and bench phases can flip ``PAGED_APPEND_FLASH_MIN_W``
    at runtime (the pattern serve/scheduler.py established for
    ``prefill_chunk``); each jitted caller traces the decision once per
    static shape."""
    return env_int("PAGED_APPEND_FLASH_MIN_W", 2048)


def _flash_append_policy(window: int, append_impl: str, min_w: int,
                         hd: int = _FLASH_HD_REF) -> bool:
    """The pure dispatch rule for the append path on TPU, split from
    the platform guard so CPU tests can pin the decision table
    hardware-free (tests/test_flash_append_geometry.py):

    - ``PAGED_APPEND_IMPL=flash``  -> flash kernel at EVERY window;
    - ``PAGED_APPEND_IMPL=kernel`` -> never (the round-4 block kernel
      owns the dispatch upstream);
    - otherwise flash iff ``min_w > 0`` and the window reaches the
      GEOMETRY-SCALED boundary ``max(256, min_w * hd / 1024)`` where
      ``hd = Hkv * head_dim``.

    Why the scaling (round-18): the round-8 boundary (2048) was
    measured at hd=1024. Per window token, the gather path pays hd
    bytes of materialised copy PLUS a geometry-invariant index/mask
    overhead, while the flash kernel pays the same hd bytes streamed
    once plus a per-chunk fixed cost that the hd-aware chunk budget
    AMORTISES OVER MORE TOKENS as hd shrinks (same VMEM bytes per
    chunk). Narrow-KV geometries therefore cross over earlier in
    tokens: at bench-moe's hd=512 the boundary halves to W >= 1024 —
    squarely inside the windows where BASELINE.md round-5 recorded the
    ~1.3 ms MoE paged-walk gap the gather path was paying. The floor
    keeps sub-2-chunk windows on gather everywhere.
    """
    if append_impl == "flash":
        return True
    if append_impl == "kernel":
        return False
    if min_w <= 0:
        return False
    return window >= max(_FLASH_MIN_W_FLOOR,
                         min_w * hd // _FLASH_HD_REF)


def _flash_append_wanted(window: int, hd: int = _FLASH_HD_REF) -> bool:
    if jax.devices()[0].platform != "tpu":
        return False            # non-interpret pallas_call needs the TPU
    return _flash_append_policy(window, _APPEND_IMPL,
                                _flash_append_min_w(), hd)


def effective_flash_min_w(hd: int = _FLASH_HD_REF) -> int:
    """The flash-append engagement boundary as ONE number, for gauges
    and logs (serve/scheduler.py's ``paged_flash_min_w``): 0 = the
    kernel cannot engage in this process (non-TPU platform, disabled,
    or the block-kernel override), 1 = the flash override (every
    window), else the geometry-scaled min-W threshold for ``hd =
    Hkv * head_dim`` (the scheduler passes its model's). Kept next to
    _flash_append_policy so the dispatch rule has exactly one home."""
    if jax.devices()[0].platform != "tpu":
        return 0
    if _APPEND_IMPL == "flash":
        return 1
    if _APPEND_IMPL == "kernel":
        return 0
    min_w = _flash_append_min_w()
    if min_w <= 0:
        return 0
    return max(_FLASH_MIN_W_FLOOR, min_w * hd // _FLASH_HD_REF)


def _flash_append_kernel_body(quantized: bool, page_size: int, pages: int,
                              chunk_pages: int, num_chunks: int, rep: int,
                              scale: float, compute_dtype):
    """Build the multi-chunk flash-append kernel body: ONE program per
    (row, chunk) of a ``(B, num_chunks)`` grid — the split-K /
    flash-decoding shape (module docstring, round-8). The chunk axis is
    the grid's minor dimension, so for a fixed row the chunk programs
    run back to back and the online-softmax state (m, l, acc) lives in
    VMEM **scratch accumulators** that persist across them — VMEM holds
    one bounded chunk's tiles, never a whole window, which is what
    cleared the round-5 VMEM stack OOM. Structure:

    - **append semantics**: chunk 0 INITIALISES the scratch state with
      the current token's term (m = s_cur, l = 1, acc = v_cur) — exactly
      the extra softmax term paged_attention_append's gather path
      merges, so pool writes still batch after the layer scan. The last
      chunk normalises and writes the output block.
    - **cross-program double buffering**: each program issues the NEXT
      chunk's page DMAs (rolling over to the next row's chunk 0 at row
      boundaries) before waiting on its own, into 2-slot DMA scratch
      indexed by global step parity — the grid replaces the round-5
      kernel-internal chunk loop, so launch overhead amortises across
      programs and no program serialises a whole window's DMA waits.
    - **partial last chunks / non-chunk-multiple windows**: the page
      walk index clamps to ``pages - 1`` (a redundant re-fetch of the
      last real page) instead of skipping the DMA — uninitialised VMEM
      garbage can be NaN, and a NaN row poisons the p.v dot even at
      zero probability; clamped rows carry positions >= the window and
      mask to NEG_INF like any dead slot.
    - **int8 pools** (``quantized``): the per-page scale rows
      ([Hkv, ps_pad] f32, the head-major layout paged_kv.py stores for
      kernel DMAs) ride the same DMA slots; k scales fold into the
      scores, v scales into the probabilities — the same
      fold-outside-the-dots contract as the gather path, so HBM sees
      int8 KV only.
    - **selection-matmul GQA math** (from _append_kernel, the round-4
      VPU win): scores run as ONE [Ct, HD] x [HD, Hq] dot per chunk and
      the softmax chain on full-width [Ct, Hq] arrays; the scale folds
      are one [Ct, Hkv] x [Hkv, Hq] expander dot each.
    - ``compute_dtype``: bf16 on hardware (the MXU's preferred operand
      dtype; int8 -> bf16 is the cheap unpack), f32 in interpret mode so
      the CPU parity tests pin the kernel against the oracle at f32
      precision instead of bf16 rounding.
    """
    def body(*refs):
        if quantized:
            (pt_ref, len_ref, layer_ref, q_ref, kc_ref, vc_ref, k_hbm,
             v_hbm, ks_hbm, vs_hbm, o_ref, kbuf, vbuf, ksbuf, vsbuf,
             m_ref, l_ref, acc_ref, sems) = refs
        else:
            (pt_ref, len_ref, layer_ref, q_ref, kc_ref, vc_ref, k_hbm,
             v_hbm, o_ref, kbuf, vbuf, m_ref, l_ref, acc_ref, sems) = refs
            ksbuf = vsbuf = ks_hbm = vs_hbm = None
        b = pl.program_id(0)
        c = pl.program_id(1)
        ly = layer_ref[0]
        length = len_ref[b]

        def dma(slot, bb, cc, i: int):
            # Clamped page-walk index: see the docstring's partial-chunk
            # note. pt entries past a row's allocation are 0 (garbage
            # page) by the pool contract, so every fetch is in bounds.
            j = jnp.minimum(cc * chunk_pages + i, pages - 1)
            page = pt_ref[bb, j]
            copies = [
                pltpu.make_async_copy(k_hbm.at[ly, page], kbuf.at[slot, i],
                                      sems.at[0, slot, i]),
                pltpu.make_async_copy(v_hbm.at[ly, page], vbuf.at[slot, i],
                                      sems.at[1, slot, i]),
            ]
            if quantized:
                copies += [
                    pltpu.make_async_copy(ks_hbm.at[ly, page],
                                          ksbuf.at[slot, i],
                                          sems.at[2, slot, i]),
                    pltpu.make_async_copy(vs_hbm.at[ly, page],
                                          vsbuf.at[slot, i],
                                          sems.at[3, slot, i]),
                ]
            return copies

        def start_chunk(slot, bb, cc) -> None:
            for i in range(chunk_pages):
                for d in dma(slot, bb, cc, i):
                    d.start()

        def wait_chunk(slot, bb, cc) -> None:
            for i in range(chunk_pages):
                for d in dma(slot, bb, cc, i):
                    d.wait()

        # Global step index orders the whole grid's chunk walk; its
        # parity picks the DMA slot (num_chunks may be odd, so parity
        # must run THROUGH row boundaries, not reset per row).
        step = b * num_chunks + c
        slot = jax.lax.rem(step, 2)

        @pl.when(step == 0)
        def _warmup():
            start_chunk(0, b, c)

        # Prefetch the next chunk — the next row's chunk 0 at a row
        # boundary — before waiting on our own.
        nb = jnp.where(c + 1 == num_chunks, b + 1, b)
        nc = jnp.where(c + 1 == num_chunks, 0, c + 1)

        @pl.when(step + 1 < pl.num_programs(0) * pl.num_programs(1))
        def _prefetch():
            start_chunk(jax.lax.rem(step + 1, 2), nb, nc)

        q = q_ref[0].astype(jnp.float32)                 # [Hq, D]
        Hq, D = q.shape
        Hkv = Hq // rep
        HD = Hkv * D

        # Constant selection matrices — shared with _append_kernel
        # (_gqa_selection_matrices): the round-4 VPU win's machinery.
        sel, blockm, blockm_t, expt = _gqa_selection_matrices(
            Hq, Hkv, D, rep)
        sel_c = sel.astype(compute_dtype)

        # Q stacked into its kv block: [HD, Hq].
        q_cols = jax.lax.dot(sel_c, q.T.astype(compute_dtype),
                             preferred_element_type=jnp.float32)
        q_blk = jnp.where(blockm, q_cols.astype(compute_dtype),
                          jnp.zeros((), compute_dtype))          # [HD, Hq]

        @pl.when(c == 0)
        def _seed():
            # Append init: state = the current token's softmax term at
            # FULL precision (p_cur = exp(s_cur - m) = 1 at m = s_cur).
            # State layout matches the chunk math: m/l [1, Hq],
            # acc [Hq, D].
            kcur = jax.lax.dot(expt, kc_ref[0].astype(jnp.float32),
                               preferred_element_type=jnp.float32)
            vcur = jax.lax.dot(expt, vc_ref[0].astype(jnp.float32),
                               preferred_element_type=jnp.float32)
            m_ref[:] = jnp.sum(q * kcur, axis=-1,
                               keepdims=True).T * scale          # [1, Hq]
            l_ref[:] = jnp.ones((1, Hq), jnp.float32)
            acc_ref[:] = vcur                                    # [Hq, D]

        wait_chunk(slot, b, c)
        Ct = chunk_pages * page_size
        kflat = kbuf[slot].reshape(Ct, HD).astype(compute_dtype)
        vflat = vbuf[slot].reshape(Ct, HD).astype(compute_dtype)
        s = jax.lax.dot(kflat, q_blk,
                        preferred_element_type=jnp.float32) * scale
        if quantized:
            # [Ct, Hkv] scale columns -> [Ct, Hq] via the expander dot
            # (one MXU op; per-page segment concats measured
            # overhead-bound on the VPU).
            sk = jnp.concatenate(
                [ksbuf[slot][i, :, :page_size].T
                 for i in range(chunk_pages)], axis=0)           # [Ct, Hkv]
            s = s * jax.lax.dot(sk, expt.T,
                                preferred_element_type=jnp.float32)
        pos = c * chunk_pages * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (Ct, 1), dimension=0)
        s = jnp.where(pos < length, s, NEG_INF)                  # [Ct, Hq]

        m_prev = m_ref[:]                                        # [1, Hq]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=0, keepdims=True))
        alpha = jnp.exp(m_prev - m_cur)                          # [1, Hq]
        probs = jnp.exp(s - m_cur)                               # [Ct, Hq]
        # Denominator sums the UNSCALED probabilities (v scales fold
        # into the p.v dot only — the gather path's contract).
        l_ref[:] = l_ref[:] * alpha + jnp.sum(probs, axis=0,
                                              keepdims=True)
        if quantized:
            sv = jnp.concatenate(
                [vsbuf[slot][i, :, :page_size].T
                 for i in range(chunk_pages)], axis=0)           # [Ct, Hkv]
            probs = probs * jax.lax.dot(
                sv, expt.T, preferred_element_type=jnp.float32)
        out_full = jax.lax.dot(probs.T.astype(compute_dtype), vflat,
                               preferred_element_type=jnp.float32)
        out_full = jnp.where(blockm_t, out_full, 0.0)            # [Hq, HD]
        acc_ref[:] = acc_ref[:] * alpha.T + jax.lax.dot(
            out_full.astype(compute_dtype), sel_c,
            preferred_element_type=jnp.float32)                  # [Hq, D]
        m_ref[:] = m_cur

        @pl.when(c == num_chunks - 1)
        def _finalise():
            # l >= 1 always: the current token's own term seeds it.
            o_ref[0] = (acc_ref[:] / l_ref[:].T).astype(o_ref.dtype)

    return body


@functools.partial(jax.jit,
                   static_argnames=("pages", "quantized", "interpret"))
def _paged_attention_flash_append(q, k_cur, v_cur, k_pages, v_pages,
                                  k_scale, v_scale, page_table, lengths,
                                  layer, *, pages: int, quantized: bool,
                                  interpret: bool = False):
    """Multi-chunk flash-append dispatch: grid ``(B, num_chunks)``, one
    bounded chunk of manually-DMA'd pages (and scale rows) per program,
    online softmax carried in VMEM scratch across the chunk axis and
    seeded with the current token (_flash_append_kernel_body). HBM reads
    each page exactly once per (layer, step) — no gathered-window
    materialisation — which is what makes it the long-window win and,
    since round 8, the DEFAULT dispatch at W >= 2048 on TPU; below
    ``_flash_append_min_w()`` the gather path's XLA fusion amortises
    better and stays default (module docstring has the measured
    ladder)."""
    B, Hq, D = q.shape
    L, N, page_size, Hkv, _ = k_pages.shape
    rep = Hq // Hkv
    scale = 1.0 / (D ** 0.5)
    pt = page_table[:, :pages].astype(jnp.int32)
    layer = jnp.asarray(layer, jnp.int32).reshape(1)
    # Chunk budget in TOKENS, bounded by the VMEM stack, NOT by the
    # window: _FLASH_CHUNK_TOK_BYTES derives the per-dtype chunk (1024
    # int8 / 512 bf16 / 256 f32 tokens at the hd=1024 calibration
    # geometry), scaled by _FLASH_HD_REF / hd so the chunk's VMEM BYTES
    # stay constant across KV geometries — narrow-KV models (bench-moe:
    # hd=512) carry 2x the tokens per chunk for the same VMEM, halving
    # the per-chunk fixed cost per window token. The grid — not a
    # bigger chunk — is what amortises per-chunk overhead now, so
    # chunks never grow with W and the round-5 whole-chunk VMEM OOM
    # cannot recur.
    hd = Hkv * D
    tok_budget = max(page_size,
                     _FLASH_CHUNK_TOK_BYTES * _FLASH_HD_REF
                     // (hd * k_pages.dtype.itemsize))
    chunk_pages = max(1, min(pages, tok_budget // page_size))
    num_chunks = -(-pages // chunk_pages)
    # bf16 math on hardware; f32 in interpret mode so CPU parity tests
    # pin against the oracle at full precision (the body's dataflow is
    # identical — only the dot operand dtype changes).
    compute_dtype = jnp.float32 if interpret else jnp.bfloat16

    in_specs = [
        pl.BlockSpec((1, Hq, D), lambda b, c, pt, ln, ly: (b, 0, 0)),
        pl.BlockSpec((1, Hkv, D), lambda b, c, pt, ln, ly: (b, 0, 0)),
        pl.BlockSpec((1, Hkv, D), lambda b, c, pt, ln, ly: (b, 0, 0)),
        pl.BlockSpec(memory_space=pl.ANY),      # k pool stays in HBM
        pl.BlockSpec(memory_space=pl.ANY),      # v pool stays in HBM
    ]
    operands = [q, k_cur, v_cur, k_pages, v_pages]
    scratch = [
        pltpu.VMEM((2, chunk_pages, page_size, Hkv, D), k_pages.dtype),
        pltpu.VMEM((2, chunk_pages, page_size, Hkv, D), v_pages.dtype),
    ]
    n_sem = 2
    if quantized:
        ps_pad = k_scale.shape[-1]
        in_specs += [
            pl.BlockSpec(memory_space=pl.ANY),  # k scales stay in HBM
            pl.BlockSpec(memory_space=pl.ANY),  # v scales stay in HBM
        ]
        operands += [k_scale, v_scale]
        scratch += [
            pltpu.VMEM((2, chunk_pages, Hkv, ps_pad), jnp.float32),
            pltpu.VMEM((2, chunk_pages, Hkv, ps_pad), jnp.float32),
        ]
        n_sem = 4
    # Cross-chunk online-softmax state (persists across the grid's
    # chunk axis; re-seeded at every row's chunk 0).
    scratch += [
        pltpu.VMEM((1, Hq), jnp.float32),       # running max m
        pltpu.VMEM((1, Hq), jnp.float32),       # running sum l
        pltpu.VMEM((Hq, D), jnp.float32),       # unnormalised acc
    ]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,       # page_table, lengths, layer
        grid=(B, num_chunks),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, Hq, D),
                               lambda b, c, pt, ln, ly: (b, 0, 0)),
        scratch_shapes=scratch + [
            pltpu.SemaphoreType.DMA((n_sem, 2, chunk_pages))],
    )
    return pl.pallas_call(
        _flash_append_kernel_body(quantized, page_size, pages, chunk_pages,
                                  num_chunks, rep, scale, compute_dtype),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        interpret=interpret,
    )(pt, lengths.astype(jnp.int32), layer, *operands)


@functools.partial(jax.jit, static_argnames=("pages", "interpret"))
def _paged_attention_flash(q, k_pages, v_pages, page_table, lengths, layer,
                           *, pages: int, interpret: bool = False):
    B, Hq, D = q.shape
    L, N, page_size, Hkv, _ = k_pages.shape
    rep = Hq // Hkv
    scale = 1.0 / (D ** 0.5)
    pt = page_table[:, :pages].astype(jnp.int32)
    layer = jnp.asarray(layer, jnp.int32).reshape(1)
    chunk_pages = min(pages, _FLASH_CHUNK_PAGES)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,       # page_table, lengths, layer
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, Hq, D), lambda b, pt, ln, ly: (b, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),      # k pool stays in HBM
            pl.BlockSpec(memory_space=pl.ANY),      # v pool stays in HBM
        ],
        out_specs=pl.BlockSpec((1, Hq, D), lambda b, pt, ln, ly: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, chunk_pages, page_size, Hkv, D), k_pages.dtype),
            pltpu.VMEM((2, chunk_pages, page_size, Hkv, D), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, 2, chunk_pages)),
        ],
    )
    return pl.pallas_call(
        functools.partial(_flash_kernel, page_size=page_size, pages=pages,
                          chunk_pages=chunk_pages, rep=rep, scale=scale),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        interpret=interpret,
    )(pt, lengths.astype(jnp.int32), layer, q, k_pages, v_pages)


def paged_attention(q: jax.Array, k_pages: jax.Array, v_pages: jax.Array,
                    page_table: jax.Array, lengths: jax.Array,
                    layer: jax.Array, *, pages: int,
                    interpret: bool = False,
                    impl: str | None = None,
                    k_scale: jax.Array | None = None,
                    v_scale: jax.Array | None = None) -> jax.Array:
    """Decode attention for one layer over the paged pool.

    q: [B, Hq, D] (one token per row); k_pages/v_pages: the full pool
    [L, N, page_size, Hkv, D] (stays in HBM — ``layer`` selects inside
    the op, so no layer copy is materialised); page_table: [B, >=pages];
    lengths: [B] tokens to attend per row (including the slot this step
    wrote — callers pass ``cache.lengths + 1``); layer: scalar int32;
    pages: static page-walk count (the serving window ladder:
    ``ceil(window / page_size)``); impl: gather | flash | kernel (None =
    the ``PAGED_ATTN_IMPL`` env default, gather). For an int8 pool
    (ops/paged_kv quantized=True) pass ``k_scale``/``v_scale``
    (head-major [L, N, Hkv, ps_pad] f32, ps_pad = page_size padded to a
    128 multiple — PagedKVCache's storage layout) — gather-impl only. Returns [B, Hq, D]
    in q.dtype.
    """
    if impl is None:
        impl = _DEFAULT_IMPL
    if k_scale is not None:
        if impl != "gather":
            raise ValueError(
                f"int8 KV pools support impl='gather' only, got {impl!r}")
        return _paged_attention_gather_quant(
            q, k_pages, v_pages, k_scale, v_scale, page_table, lengths,
            layer, pages=pages)
    if impl == "gather":
        return _paged_attention_gather(q, k_pages, v_pages, page_table,
                                       lengths, layer, pages=pages)
    if impl == "flash":
        return _paged_attention_flash(q, k_pages, v_pages, page_table,
                                      lengths, layer, pages=pages,
                                      interpret=interpret)
    if impl != "kernel":
        raise ValueError(f"impl must be gather|flash|kernel, got {impl!r}")
    return _paged_attention_kernel(q, k_pages, v_pages, page_table, lengths,
                                   layer, pages=pages, interpret=interpret)


def paged_attention_reference(q: jax.Array, k_pages: jax.Array,
                              v_pages: jax.Array, page_table: jax.Array,
                              lengths: jax.Array, layer,
                              *, pages: int) -> jax.Array:
    """jnp oracle: gather the pages dense slot-by-slot, run masked GQA
    attention (models/layers.attend_gqa). Same signature/semantics as
    :func:`paged_attention`; kept deliberately index-naive (per-token
    fetches, no whole-page reshape tricks) so it stays an independent
    check on both production implementations."""
    from ..models.layers import attend_gqa

    B = q.shape[0]
    page_size = k_pages.shape[2]
    window = pages * page_size
    pos = jnp.arange(window)
    phys = page_table[:, :pages][:, pos // page_size]      # [B, window]
    slot = jnp.broadcast_to(pos % page_size, (B, window))
    k = k_pages[layer][phys, slot]                         # [B, window, Hkv, D]
    v = v_pages[layer][phys, slot]
    mask = (pos[None, :] < lengths[:, None])[:, None, None, :]  # [B,1,1,W]
    return attend_gqa(q[:, None], k, v, mask)[:, 0]        # [B, Hq, D]
