"""Reliable datagram transport + NAT hole punching support.

The reference's node listens on TCP *and* QUIC-v1
(go/cmd/node/main.go:139-140) and maps NAT ports
(libp2p.NATPortMap(), go/cmd/node/main.go:143). The in-tree equivalent
is UDP-based direct connectivity: a dialer and a NAT'd listener exchange
their relay-observed UDP endpoints over the relay control channel
(relay.py PUNCH coordination), fire probe datagrams at each other to
open both NAT mappings, and then run the exact same Noise-XX-style
handshake and ChaCha20-Poly1305 framing as the TCP transport — over a
:class:`ReliableDgram`, which duck-types the blocking-socket surface
(``sendall``/``recv``/``settimeout``/``shutdown``/``close``) on top of a
connected UDP socket. Message bytes then flow peer-to-peer; the relay
carries only the few-hundred-byte coordination exchange, not the
conversation (unlike a circuit splice).

Reliability is deliberately minimal — stop-and-wait with per-chunk acks
and retransmission. Chat messages are a few KB (SURVEY.md §2 C2 wire
schema), so a congestion-controlled QUIC reimplementation would be all
cost and no observable difference; the layer is below encryption, so a
forged/replayed datagram at worst perturbs framing and fails AEAD
authentication upstream.

Wire format (one datagram each):
    b"D" seq:8 payload   in-order data chunk
    b"A" seq:8           cumulative-style ack of exactly ``seq``
    b"F" seq:8           sender finished after ``seq-1`` (acked like data)
    b"P"                 punch probe — opens the NAT mapping, else ignored
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
import time
from typing import Optional

from ..utils.log import get_logger

log = get_logger("p2p.udp")

# Payload bytes per datagram: safely under common path MTUs so the IP
# layer never fragments (fragment loss would multiply retransmissions).
CHUNK = 1152
_ACK_TIMEOUT_S = 0.25
_DEFAULT_SEND_TIMEOUT_S = 10.0
PUNCH_PROBES = 3
PUNCH_INTERVAL_S = 0.05


class ReliableDgram:
    """Socket-shaped reliable byte stream over a connected UDP socket.

    One pump thread per instance reads datagrams: acks for in-flight
    sends are dispatched to the sending thread, in-order data chunks
    append to the receive buffer, duplicates are re-acked (their ack may
    have been lost). ``sendall`` is stop-and-wait per chunk; ``recv``
    blocks on the buffer like a stream socket and returns b"" at the
    remote's FIN.
    """

    def __init__(self, sock: socket.socket, peer: tuple[str, int],
                 send_timeout_s: float = _DEFAULT_SEND_TIMEOUT_S) -> None:
        self._sock = sock
        self._peer = peer
        # Retransmission budget per chunk: bounds how long an unreachable
        # peer (UDP-hostile network after a "successful" coordination
        # exchange) can stall the caller — the hole-punch dialer passes
        # its dial timeout here so punch failures fall back to the relay
        # circuit within the /send deadline.
        self._max_retries = max(1, int(send_timeout_s / _ACK_TIMEOUT_S))
        sock.connect(peer)          # filter to the punched peer's datagrams
        self._send_seq = 0
        self._acks: dict[int, threading.Event] = {}
        self._acks_mu = threading.Lock()
        self._recv_next = 0
        self._recv_buf = bytearray()
        self._fin_seq: Optional[int] = None
        self._cond = threading.Condition()
        self._timeout: Optional[float] = None
        self._closed = threading.Event()
        # Intended hierarchy (machine-checked by graftcheck lock-order):
        # the sender path holds _send_mu across a whole stop-and-wait
        # chunk exchange and takes _acks_mu briefly inside it; nothing
        # may ever take them in the other order.
        # lock-order: ReliableDgram._send_mu < ReliableDgram._acks_mu
        self._send_mu = threading.Lock()
        self._fin_sent = False
        self._pump = threading.Thread(target=self._pump_loop, daemon=True)
        self._pump.start()

    # -- pump ----------------------------------------------------------------

    def _pump_loop(self) -> None:
        sock = self._sock
        while not self._closed.is_set():
            try:
                data = sock.recv(65536)
            except OSError:
                break
            if not data:
                continue
            kind = data[:1]
            if kind == b"P" or len(data) < 9:
                continue
            seq = struct.unpack(">Q", data[1:9])[0]
            if kind == b"A":
                with self._acks_mu:
                    ev = self._acks.get(seq)
                if ev is not None:
                    ev.set()
            elif kind == b"D":
                if seq == self._recv_next:
                    with self._cond:
                        self._recv_buf.extend(data[9:])
                        self._recv_next += 1
                        self._cond.notify_all()
                if seq < self._recv_next:   # delivered (now or earlier): ack
                    self._send_ctrl(b"A", seq)
                # Out-of-order future chunks are dropped — the sender is
                # stop-and-wait, so the only future chunk is seq ==
                # recv_next after a lost predecessor retransmits.
            elif kind == b"F":
                if seq <= self._recv_next:
                    with self._cond:
                        self._fin_seq = seq
                        self._cond.notify_all()
                    self._send_ctrl(b"A", seq)
        with self._cond:
            if self._fin_seq is None:
                self._fin_seq = self._recv_next     # EOF on close
            self._cond.notify_all()

    def _send_ctrl(self, kind: bytes, seq: int, payload: bytes = b"") -> None:
        try:
            self._sock.send(kind + struct.pack(">Q", seq) + payload)
        except OSError:
            pass

    def _send_reliable(self, kind: bytes, seq: int, payload: bytes) -> None:
        ev = threading.Event()
        with self._acks_mu:
            self._acks[seq] = ev
        try:
            for _ in range(self._max_retries):
                self._send_ctrl(kind, seq, payload)
                if ev.wait(_ACK_TIMEOUT_S):
                    return
                if self._closed.is_set():
                    raise OSError("dgram stream closed")
            raise OSError(
                f"no ack for seq {seq} after {self._max_retries} tries")
        finally:
            with self._acks_mu:
                self._acks.pop(seq, None)

    # -- socket surface ------------------------------------------------------

    def sendall(self, data: bytes) -> None:
        with self._send_mu:
            for off in range(0, len(data), CHUNK) or [0]:
                chunk = data[off: off + CHUNK]
                self._send_reliable(b"D", self._send_seq, chunk)
                self._send_seq += 1

    def recv(self, n: int) -> bytes:
        deadline = (time.monotonic() + self._timeout
                    if self._timeout is not None else None)
        with self._cond:
            while not self._recv_buf:
                if (self._fin_seq is not None
                        and self._recv_next >= self._fin_seq):
                    return b""                      # clean EOF
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise socket.timeout("dgram recv timed out")
                self._cond.wait(remaining)
            out = bytes(self._recv_buf[:n])
            del self._recv_buf[:n]
            return out

    def settimeout(self, t: Optional[float]) -> None:
        self._timeout = t

    def shutdown(self, how: int) -> None:
        if how not in (socket.SHUT_WR, socket.SHUT_RDWR):
            return
        with self._send_mu:
            if self._fin_sent:          # a second FIN would never be acked
                return
            self._fin_sent = True
            # The peer's reader may be blocked in recv with NO timeout
            # (the post-handshake steady state), so the FIN must be
            # retransmitted on loss — but briefly: ~2 s covers datagram
            # loss without wedging the closing thread for the full
            # per-chunk budget when the peer has vanished.
            old = self._max_retries
            self._max_retries = min(old, 8)
            try:
                self._send_reliable(b"F", self._send_seq, b"")
            except OSError:
                pass
            finally:
                self._max_retries = old
            self._send_seq += 1

    def close(self) -> None:
        if self._closed.is_set():
            return
        try:
            self.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._closed.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._cond:
            self._cond.notify_all()

    def getsockname(self):
        return self._sock.getsockname()


# -- NAT endpoint discovery + punching ---------------------------------------

def observe_udp_addr(sock: socket.socket, relay_host: str, relay_port: int,
                     timeout: float = 3.0,
                     attempts: int = 3) -> Optional[tuple[str, int]]:
    """Learn this socket's relay-observed (post-NAT) endpoint: send a
    JSON ``observe`` datagram to the relay's UDP port (relay.py answers
    with the source address it saw — STUN-lite). Returns None when the
    relay doesn't answer (old relay / UDP blocked); callers fall back to
    the local sockname, which is correct on un-NAT'd paths."""
    nonce = os.urandom(8).hex()
    req = json.dumps({"type": "observe", "nonce": nonce}).encode()
    old_timeout = sock.gettimeout()
    sock.settimeout(timeout / attempts)
    try:
        for _ in range(attempts):
            try:
                sock.sendto(req, (relay_host, relay_port))
                data, _ = sock.recvfrom(2048)
                resp = json.loads(data.decode())
                if resp.get("nonce") == nonce and resp.get("addr"):
                    h, p = resp["addr"]
                    return str(h), int(p)
            except (OSError, ValueError, json.JSONDecodeError):
                continue
        return None
    finally:
        sock.settimeout(old_timeout)


def punch(sock: socket.socket, peer: tuple[str, int]) -> None:
    """Fire probe datagrams at the peer's observed endpoint: the first
    outbound packet opens this side's NAT mapping; a few repeats cover
    probe loss while the far side's mapping opens."""
    for _ in range(PUNCH_PROBES):
        try:
            sock.sendto(b"P", peer)
        except OSError:
            return
        time.sleep(PUNCH_INTERVAL_S)
