"""Multiaddr parsing/formatting.

We keep the reference's textual address shape so directory payloads are
wire-compatible (addrs built at go/cmd/node/main.go:176-181):

    /ip4/127.0.0.1/tcp/4001/p2p/<peer-id>

plus the libp2p circuit form for relayed reachability (the reference ships a
relay daemon, go/cmd/relay/main.go, whose addresses take this shape):

    /ip4/<relay-ip>/tcp/<relay-port>/p2p/<relay-id>/p2p-circuit/p2p/<peer-id>

Only the components we route on are modelled (ip4/dns4, tcp, p2p,
p2p-circuit); unknown components raise.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass
class Multiaddr:
    host: str                       # ip4 or dns4 value
    port: int                       # tcp port
    peer_id: Optional[str] = None   # trailing /p2p/<id> (target)
    # Relay circuit: when set, (host, port, relay_peer_id) address the relay
    # and peer_id addresses the target behind it.
    relay_peer_id: Optional[str] = None
    is_circuit: bool = False

    @classmethod
    def parse(cls, s: str) -> "Multiaddr":
        parts = [p for p in s.strip().split("/") if p != ""]
        host: Optional[str] = None
        port: Optional[int] = None
        peer_ids: list[str] = []
        is_circuit = False
        i = 0
        while i < len(parts):
            key = parts[i]
            if key in ("ip4", "ip6", "dns4", "dns6", "dns"):
                host = parts[i + 1]
                i += 2
            elif key == "tcp":
                port = int(parts[i + 1])
                i += 2
            elif key == "p2p":
                peer_ids.append(parts[i + 1])
                i += 2
            elif key == "p2p-circuit":
                is_circuit = True
                i += 1
            elif key == "quic-v1" or key == "quic":
                # The reference listens on QUIC too (go/cmd/node/main.go:140);
                # our transport is TCP-only, so QUIC addrs parse but carry the
                # same host/port for dialing purposes.
                i += 1
            elif key == "udp":
                port = int(parts[i + 1])
                i += 2
            else:
                raise ValueError(f"unsupported multiaddr component /{key} in {s!r}")
        if host is None or port is None:
            raise ValueError(f"multiaddr missing host/port: {s!r}")
        if is_circuit:
            if len(peer_ids) != 2:
                raise ValueError(f"circuit multiaddr needs relay and target ids: {s!r}")
            return cls(host=host, port=port, peer_id=peer_ids[1],
                       relay_peer_id=peer_ids[0], is_circuit=True)
        return cls(host=host, port=port,
                   peer_id=peer_ids[0] if peer_ids else None)

    def __str__(self) -> str:
        base = f"/ip4/{self.host}/tcp/{self.port}"
        if self.is_circuit:
            return f"{base}/p2p/{self.relay_peer_id}/p2p-circuit/p2p/{self.peer_id}"
        if self.peer_id:
            return f"{base}/p2p/{self.peer_id}"
        return base

    def with_peer(self, peer_id: str) -> "Multiaddr":
        """Encapsulate a /p2p/<id> suffix (go/cmd/node/main.go:179)."""
        return Multiaddr(self.host, self.port, peer_id=peer_id,
                         relay_peer_id=self.relay_peer_id, is_circuit=self.is_circuit)

    def circuit_via(self, relay_id: str) -> "Multiaddr":
        return Multiaddr(self.host, self.port, peer_id=self.peer_id,
                         relay_peer_id=relay_id, is_circuit=True)
