"""Authenticated, encrypted P2P streams over TCP.

From-scratch equivalent of the reference's libp2p host + noise-secured
streams (SURVEY.md §1 L0; host construction go/cmd/node/main.go:137-144,
stream open/write/close go/cmd/node/main.go:245-261, handler
go/cmd/node/main.go:158-172). Not a port — a minimal Noise-XX-style design:

Handshake (dialer D -> listener L), all over one TCP connection:

    1. D->L  plaintext frame: eph_pub_D                      (32B X25519)
    2. L->D  plaintext frame: eph_pub_L || static_pub_L || sig_L
             where sig_L = Ed25519(static_L, "hs1" || eph_D || eph_L || static_pub_L)
    3. both derive: shared = X25519(eph_D, eph_L)
             k_D2L, k_L2D = HKDF-SHA256(shared, salt=eph_D||eph_L, info=PROTO, 64B)
    4. D->L  encrypted frame: static_pub_D || sig_D
             where sig_D = Ed25519(static_D, "hs2" || eph_D || eph_L || static_pub_D)
    5. D->L  encrypted frame: protocol ID (stream dispatch, the equivalent
             of libp2p protocol negotiation for SetStreamHandler)

Both sides authenticate with their static Ed25519 identity; peer IDs are
self-certifying (identity.py) so the dialer verifies the listener against
the directory record and the listener learns the authenticated remote peer.
Data frames are ChaCha20-Poly1305 with a per-direction 96-bit counter nonce,
4-byte big-endian length prefix. A stream carries whole messages: the sender
writes frames and closes; the receiver reads frames until EOF (the
reference's one-stream-per-message framing, go/cmd/node/main.go:160).

Relay circuits: `dial` transparently tunnels through a relay for
``/p2p-circuit`` multiaddrs — the end-to-end handshake runs *through* the
relay's byte pipe, so the relay never sees plaintext or holds keys (same
property as libp2p circuit-relay-v2, go/cmd/relay/main.go:37).
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
import time
from typing import Callable, Optional

try:
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PublicKey,
    )
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
        X25519PublicKey,
    )
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
    from cryptography.hazmat.primitives.kdf.hkdf import HKDF
except ImportError:
    # INSECURE stdlib dev fallback, explicit opt-in only (P2P_DEV_CRYPTO=1
    # — see p2p/devcrypto.py for exactly what is and is not provided).
    from .devcrypto import require_dev_crypto
    require_dev_crypto("p2p.transport")
    from .devcrypto import (            # type: ignore[assignment]
        ChaCha20Poly1305,
        Ed25519PublicKey,
        HKDF,
        X25519PrivateKey,
        X25519PublicKey,
        hashes,
        serialization,
    )

from ..utils.env import env_bool
from ..utils.failpoints import failpoint
from ..utils.log import get_logger
from .addr import Multiaddr
from .identity import Identity, peer_id_to_public_key, public_key_to_peer_id

log = get_logger("p2p")

PROTO_INFO = b"/p2p-llm-chat-tpu/secure/1.0.0"
MAX_FRAME = 16 * 1024 * 1024
HANDSHAKE_TIMEOUT = 10.0

# Relay control message types (JSON, plaintext first frame on a relay conn).
RELAY_RESERVE = "reserve"
RELAY_HOP = "hop"
RELAY_ACCEPT = "accept"
RELAY_INCOMING = "incoming"
RELAY_PING = "ping"
RELAY_PONG = "pong"
# NAT hole punching (coordination only — message bytes then flow directly
# peer-to-peer over UDP, see p2p/udp.py; the relay never splices them).
RELAY_PUNCH = "punch"
RELAY_PUNCH_ACK = "punch_ack"


class HandshakeError(Exception):
    pass


# ---------------------------------------------------------------------------
# Framing primitives (shared by plaintext handshake + relay control frames)
# ---------------------------------------------------------------------------

def send_raw_frame(sock: socket.socket, data: bytes) -> None:
    sock.sendall(struct.pack(">I", len(data)) + data)


def recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def recv_raw_frame(sock: socket.socket) -> Optional[bytes]:
    header = recv_exact(sock, 4)
    if header is None:
        return None
    (length,) = struct.unpack(">I", header)
    if length > MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    if length == 0:
        return b""
    return recv_exact(sock, length)


def send_json_frame(sock: socket.socket, obj: dict) -> None:
    send_raw_frame(sock, json.dumps(obj).encode("utf-8"))


def recv_json_frame(sock: socket.socket) -> Optional[dict]:
    raw = recv_raw_frame(sock)
    if raw is None:
        return None
    return json.loads(raw.decode("utf-8"))


# ---------------------------------------------------------------------------
# Secure stream
# ---------------------------------------------------------------------------

class SecureStream:
    """An authenticated encrypted byte-frame stream over one TCP connection."""

    def __init__(self, sock: socket.socket, send_key: bytes, recv_key: bytes,
                 remote_peer_id: str) -> None:
        self._sock = sock
        self._send = ChaCha20Poly1305(send_key)
        self._recv = ChaCha20Poly1305(recv_key)
        self._send_ctr = 0        # guarded-by: _send_lock
        self._recv_ctr = 0
        self._send_lock = threading.Lock()
        self.remote_peer_id = remote_peer_id

    def send_frame(self, data: bytes) -> None:
        with self._send_lock:
            nonce = self._send_ctr.to_bytes(12, "little")
            self._send_ctr += 1
            send_raw_frame(self._sock, self._send.encrypt(nonce, data, None))

    def recv_frame(self) -> Optional[bytes]:
        ct = recv_raw_frame(self._sock)
        if ct is None:
            return None
        nonce = self._recv_ctr.to_bytes(12, "little")
        self._recv_ctr += 1
        return self._recv.decrypt(nonce, ct, None)

    def read_all(self) -> bytes:
        """Read frames until the remote closes; concatenation of payloads.

        The receive-side analogue of the reference's ``io.ReadAll`` until
        EOF (go/cmd/node/main.go:160).
        """
        parts = []
        while True:
            f = self.recv_frame()
            if f is None:
                return b"".join(parts)
            parts.append(f)

    def close_write(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_WR)
        except OSError:
            pass

    def close(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()

    def settimeout(self, t: Optional[float]) -> None:
        self._sock.settimeout(t)


def _derive_keys(eph_priv: X25519PrivateKey, remote_eph_pub: bytes,
                 eph_d: bytes, eph_l: bytes) -> tuple[bytes, bytes]:
    shared = eph_priv.exchange(X25519PublicKey.from_public_bytes(remote_eph_pub))
    okm = HKDF(
        algorithm=hashes.SHA256(), length=64, salt=eph_d + eph_l, info=PROTO_INFO
    ).derive(shared)
    return okm[:32], okm[32:]  # (k_dialer_to_listener, k_listener_to_dialer)


def _x25519_pub_bytes(priv: X25519PrivateKey) -> bytes:
    return priv.public_key().public_bytes(
        serialization.Encoding.Raw, serialization.PublicFormat.Raw
    )


def dialer_handshake(sock: socket.socket, identity: Identity,
                     expected_peer_id: Optional[str]) -> SecureStream:
    # Failpoint: the secure-channel dial handshake. ``drop``/``error``
    # surface as the HandshakeError every dial path already degrades on
    # (node._deliver collects it and tries the next advertised addr);
    # ``raise`` exercises the same paths with an unexpected fault.
    act = failpoint("p2p.transport.handshake")
    if act is not None and act.kind in ("drop", "error"):
        raise HandshakeError(
            act.msg or "injected fault: p2p.transport.handshake")
    sock.settimeout(HANDSHAKE_TIMEOUT)
    eph = X25519PrivateKey.generate()
    eph_d = _x25519_pub_bytes(eph)
    send_raw_frame(sock, eph_d)

    msg2 = recv_raw_frame(sock)
    if msg2 is None or len(msg2) != 32 + 32 + 64:
        raise HandshakeError("bad handshake msg2")
    eph_l, static_l, sig_l = msg2[:32], msg2[32:64], msg2[64:]
    listener_pub = Ed25519PublicKey.from_public_bytes(static_l)
    try:
        listener_pub.verify(sig_l, b"hs1" + eph_d + eph_l + static_l)
    except Exception as e:
        raise HandshakeError(f"listener signature invalid: {e}") from None
    remote_peer_id = public_key_to_peer_id(listener_pub)
    if expected_peer_id is not None and remote_peer_id != expected_peer_id:
        raise HandshakeError(
            f"peer identity mismatch: expected {expected_peer_id}, got {remote_peer_id}"
        )

    k_d2l, k_l2d = _derive_keys(eph, eph_l, eph_d, eph_l)
    stream = SecureStream(sock, send_key=k_d2l, recv_key=k_l2d,
                          remote_peer_id=remote_peer_id)
    sig_d = identity.sign(b"hs2" + eph_d + eph_l + identity.public_bytes)
    stream.send_frame(identity.public_bytes + sig_d)
    sock.settimeout(None)
    return stream


def listener_handshake(sock: socket.socket, identity: Identity,
                       first_frame: Optional[bytes] = None) -> SecureStream:
    sock.settimeout(HANDSHAKE_TIMEOUT)
    eph_d = first_frame if first_frame is not None else recv_raw_frame(sock)
    if eph_d is None or len(eph_d) != 32:
        raise HandshakeError("bad handshake msg1")
    eph = X25519PrivateKey.generate()
    eph_l = _x25519_pub_bytes(eph)
    static_l = identity.public_bytes
    sig_l = identity.sign(b"hs1" + eph_d + eph_l + static_l)
    send_raw_frame(sock, eph_l + static_l + sig_l)

    k_d2l, k_l2d = _derive_keys(eph, eph_d, eph_d, eph_l)
    stream = SecureStream(sock, send_key=k_l2d, recv_key=k_d2l,
                          remote_peer_id="")
    msg3 = stream.recv_frame()
    if msg3 is None or len(msg3) != 32 + 64:
        raise HandshakeError("bad handshake msg3")
    static_d, sig_d = msg3[:32], msg3[32:]
    dialer_pub = Ed25519PublicKey.from_public_bytes(static_d)
    try:
        dialer_pub.verify(sig_d, b"hs2" + eph_d + eph_l + static_d)
    except Exception as e:
        raise HandshakeError(f"dialer signature invalid: {e}") from None
    stream.remote_peer_id = public_key_to_peer_id(dialer_pub)
    sock.settimeout(None)
    return stream


# ---------------------------------------------------------------------------
# Host
# ---------------------------------------------------------------------------

StreamHandler = Callable[[SecureStream, str], None]


class P2PHost:
    """Listens for inbound secure streams and dials outbound ones.

    The equivalent of the reference's libp2p host: ``set_stream_handler``
    mirrors ``host.SetStreamHandler`` (go/cmd/node/main.go:158), ``new_stream``
    mirrors ``host.NewStream`` (go/cmd/node/main.go:245), ``connect`` mirrors
    ``host.Connect`` (go/cmd/node/main.go:205). Additionally supports relay
    reservations + circuit dialing (the reference ships the relay daemon but
    never wires it into the node — SURVEY.md §2 C6; here it is wired).
    """

    def __init__(self, identity: Optional[Identity] = None,
                 listen_addr: str = "127.0.0.1:0",
                 advertise_host: Optional[str] = None) -> None:
        self.identity = identity or Identity.generate()
        host, _, port = listen_addr.rpartition(":")
        self._listen_host = host or "127.0.0.1"
        self._listen_port = int(port or 0)
        self._advertise_host = advertise_host or self._listen_host
        self._handlers: dict[str, StreamHandler] = {}
        self._server_sock: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._closed = threading.Event()
        self._relay_threads: list[threading.Thread] = []
        self._relay_addrs: list[Multiaddr] = []
        self._extra_addrs: list[Multiaddr] = []
        self._relay_socks: list[socket.socket] = []  # guarded-by: _relay_socks_mu
        self._relay_socks_mu = threading.Lock()
        # Negative cache for hole punching: peers whose punch failed are
        # dialed via the relay circuit directly for a while, so every
        # /send to a UDP-blocked peer doesn't re-pay the punch stall.
        # Dials run on whatever thread asked (HTTP handlers, the node
        # loop), so the read-prune-insert below must hold the lock — the
        # unlocked version lost concurrent failure entries to the prune
        # rebuild (graftcheck lock-discipline finding).
        self._punch_failed: dict[str, float] = {}  # guarded-by: _punch_mu
        self._punch_mu = threading.Lock()

    # -- lifecycle -----------------------------------------------------------

    @property
    def peer_id(self) -> str:
        return self.identity.peer_id

    def start(self) -> "P2PHost":
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self._listen_host, self._listen_port))
        s.listen(128)
        self._listen_port = s.getsockname()[1]
        self._server_sock = s
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._accept_thread.start()
        log.info("p2p host %s listening on %s:%d",
                 self.peer_id[:12], self._listen_host, self._listen_port)
        return self

    def close(self) -> None:
        self._closed.set()
        if self._server_sock is not None:
            # shutdown() before close(): a thread blocked in accept() holds a
            # kernel reference to the listening socket, so close() alone would
            # leave the port accepting connections until accept() returns.
            try:
                self._server_sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._server_sock.close()
            except OSError:
                pass
        # Close relay control connections so _relay_control_loop threads
        # blocked in recv exit and the relay drops our reservations promptly
        # (otherwise it keeps routing circuits to a closed host).
        with self._relay_socks_mu:
            socks, self._relay_socks = self._relay_socks, []
        for s in socks:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass

    def addrs(self) -> list[Multiaddr]:
        """Advertised multiaddrs, each encapsulating /p2p/<peer-id>
        (go/cmd/node/main.go:176-181), plus any extra advertised addrs
        (e.g. a NAT-PMP-mapped external address) and relay circuit addrs."""
        out = [Multiaddr(self._advertise_host, self._listen_port, peer_id=self.peer_id)]
        for extra in list(self._extra_addrs):
            out.append(extra.with_peer(self.peer_id))
        for r in self._relay_addrs:
            out.append(Multiaddr(r.host, r.port, peer_id=self.peer_id,
                                 relay_peer_id=r.peer_id, is_circuit=True))
        return out

    def add_advertised_addr(self, maddr: Multiaddr) -> None:
        """Advertise an additional dialable address for this host (the
        NAT-PMP mapper's external ip:port; parity with the addrs a
        NATPortMap'd libp2p host announces)."""
        if not any(a.host == maddr.host and a.port == maddr.port
                   for a in self._extra_addrs):
            self._extra_addrs.append(maddr)

    def remove_advertised_addr(self, maddr: Multiaddr) -> None:
        """Stop advertising an extra addr (a lapsed/moved NAT mapping)."""
        self._extra_addrs = [a for a in self._extra_addrs
                             if (a.host, a.port) != (maddr.host, maddr.port)]

    @property
    def listen_port(self) -> int:
        return self._listen_port

    @property
    def advertise_host(self) -> str:
        return self._advertise_host

    def set_stream_handler(self, protocol_id: str, handler: StreamHandler) -> None:
        self._handlers[protocol_id] = handler

    # -- inbound -------------------------------------------------------------

    def _accept_loop(self) -> None:
        assert self._server_sock is not None
        while not self._closed.is_set():
            try:
                conn, _addr = self._server_sock.accept()
            except OSError:
                return
            if self._closed.is_set():
                try:
                    conn.close()
                except OSError:
                    pass
                return
            threading.Thread(target=self._handle_inbound, args=(conn,),
                             daemon=True).start()

    def _handle_inbound(self, conn: socket.socket,
                        first_frame: Optional[bytes] = None) -> None:
        try:
            stream = listener_handshake(conn, self.identity, first_frame)
            proto_frame = stream.recv_frame()
            if proto_frame is None:
                stream.close()
                return
            protocol_id = proto_frame.decode("utf-8")
            handler = self._handlers.get(protocol_id)
            if handler is None:
                log.warning("no handler for protocol %s from %s",
                            protocol_id, stream.remote_peer_id[:12])
                stream.close()
                return
            handler(stream, stream.remote_peer_id)
        except (HandshakeError, ValueError, OSError, json.JSONDecodeError) as e:
            log.debug("inbound stream failed: %s", e)
            try:
                conn.close()
            except OSError:
                pass

    # -- outbound ------------------------------------------------------------

    def _tcp_connect(self, host: str, port: int, timeout: float) -> socket.socket:
        return socket.create_connection((host, port), timeout=timeout)

    def dial(self, maddr: Multiaddr, timeout: float = 5.0) -> SecureStream:
        """Open an authenticated secure connection to ``maddr`` (direct,
        hole-punched UDP, or relay circuit). The 5 s default matches the
        reference's /send connect deadline (go/cmd/node/main.go:235).

        Circuit addrs first attempt a UDP hole punch coordinated over
        the relay (p2p/udp.py — message bytes then flow peer-to-peer,
        matching the reference's direct-connectivity posture of QUIC +
        NATPortMap, go/cmd/node/main.go:139-143) and fall back to the
        relay's byte splice when punching fails (symmetric NATs, UDP
        blocked). ``P2P_HOLEPUNCH=0`` disables the attempt."""
        if maddr.is_circuit:
            deadline = time.monotonic() + timeout
            punch_ok = env_bool("P2P_HOLEPUNCH", True)
            # Negative cache keyed by REAL peer ids only (id-less circuit
            # addrs would all share one slot and suppress each other),
            # pruned on insert so long-lived hosts don't accumulate
            # entries forever.
            if maddr.peer_id:
                with self._punch_mu:
                    failed_at = self._punch_failed.get(maddr.peer_id)
            else:
                failed_at = None
            if failed_at is not None and time.time() - failed_at < 60.0:
                punch_ok = False
            if punch_ok:
                try:
                    return self._dial_holepunch(maddr, timeout)
                except (OSError, ConnectionError, HandshakeError,
                        ValueError) as e:
                    if maddr.peer_id:
                        now = time.time()
                        with self._punch_mu:
                            self._punch_failed = {
                                pid: t for pid, t in
                                self._punch_failed.items() if now - t < 60.0}
                            self._punch_failed[maddr.peer_id] = now
                    log.debug("hole punch to %s failed (%s); "
                              "falling back to relay circuit",
                              (maddr.peer_id or "?")[:12], e)
            # The relay fallback spends whatever of the dial deadline the
            # punch attempt left (never less than a floor so a punch that
            # consumed the budget still gets one quick relay try).
            timeout = max(0.5, deadline - time.monotonic())
            sock = self._tcp_connect(maddr.host, maddr.port, timeout)
            try:
                send_json_frame(sock, {"type": RELAY_HOP, "target": maddr.peer_id})
                resp = recv_json_frame(sock)
                if not resp or not resp.get("ok"):
                    raise ConnectionError(
                        f"relay hop refused: {resp.get('error') if resp else 'closed'}")
            except Exception:
                sock.close()
                raise
        else:
            sock = self._tcp_connect(maddr.host, maddr.port, timeout)
        try:
            return dialer_handshake(sock, self.identity, maddr.peer_id)
        except Exception:
            sock.close()
            raise

    def _dial_holepunch(self, maddr: Multiaddr,
                        timeout: float = 5.0) -> SecureStream:
        """Direct UDP connection to a NAT'd peer: learn our observed UDP
        endpoint from the relay, exchange endpoints over the relay's
        control plane, punch, then run the normal handshake over the
        reliable datagram layer."""
        from .udp import ReliableDgram, observe_udp_addr, punch

        usock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        usock.bind(("0.0.0.0", 0))
        deadline = time.monotonic() + timeout

        def left() -> float:
            rem = deadline - time.monotonic()
            if rem <= 0.05:
                raise ConnectionError("punch deadline exhausted")
            return rem

        try:
            # ONE deadline spans all three phases (observe, TCP punch
            # exchange, handshake retransmits) — each phase gets only the
            # remaining budget, so a UDP-hostile network falls back to
            # the relay circuit within the reference's 5 s /send
            # deadline instead of stacking per-phase timeouts ~2-3x it.
            observed = observe_udp_addr(usock, maddr.host, maddr.port,
                                        timeout=min(1.5, left() / 3),
                                        attempts=2)
            if observed is None:
                observed = usock.getsockname()
                if observed[0] in ("0.0.0.0", "::", ""):
                    # Without the relay's observe endpoint a wildcard
                    # bind has no routable address to advertise — a
                    # doomed punch would just stall the send path.
                    raise ConnectionError("no routable UDP endpoint")
            tsock = self._tcp_connect(maddr.host, maddr.port, left())
            try:
                tsock.settimeout(left())
                send_json_frame(tsock, {
                    "type": RELAY_PUNCH, "target": maddr.peer_id,
                    "udp_addr": [observed[0], observed[1]],
                })
                resp = recv_json_frame(tsock)
            finally:
                tsock.close()
            if not resp or not resp.get("ok") or not resp.get("udp_addr"):
                raise ConnectionError(
                    f"punch refused: {resp.get('error') if resp else 'closed'}")
            try:
                peer = (str(resp["udp_addr"][0]), int(resp["udp_addr"][1]))
            except (TypeError, ValueError, KeyError, IndexError):
                raise ConnectionError(
                    f"bad punch response addr: {resp.get('udp_addr')!r}"
                ) from None
            punch(usock, peer)
            stream = dialer_handshake(
                ReliableDgram(usock, peer, send_timeout_s=left()),
                self.identity, maddr.peer_id)
            log.info("hole-punched direct UDP path to %s",
                     stream.remote_peer_id[:12])
            return stream
        except Exception:
            usock.close()
            raise

    def new_stream(self, maddr: Multiaddr, protocol_id: str,
                   timeout: float = 5.0) -> SecureStream:
        stream = self.dial(maddr, timeout=timeout)
        stream.send_frame(protocol_id.encode("utf-8"))
        return stream

    def connect(self, maddr: Multiaddr, timeout: float = 5.0) -> str:
        """Reachability check: dial + handshake + close; returns remote peer
        id. Used for bootstrap connects (go/cmd/node/main.go:189-211)."""
        stream = self.dial(maddr, timeout=timeout)
        pid = stream.remote_peer_id
        stream.close()
        return pid

    # -- relay reservation ---------------------------------------------------

    def reserve_on_relay(self, relay_addr: Multiaddr,
                         retry_interval: float = 5.0) -> None:
        """Maintain a reservation on a relay so NAT'd peers are reachable at
        ``/.../p2p/<relay>/p2p-circuit/p2p/<us>``. Runs a daemon thread that
        holds a control connection and dials back for each incoming circuit."""
        if relay_addr.peer_id is None:
            raise ValueError("relay multiaddr must include /p2p/<relay-id>")
        self._relay_addrs.append(relay_addr)
        t = threading.Thread(target=self._relay_control_loop,
                             args=(relay_addr, retry_interval), daemon=True)
        t.start()
        self._relay_threads.append(t)

    def _relay_control_loop(self, relay_addr: Multiaddr, retry_interval: float) -> None:
        while not self._closed.is_set():
            sock = None
            try:
                sock = self._tcp_connect(relay_addr.host, relay_addr.port, 5.0)
                # Register under the lock with a _closed re-check: close()
                # sets _closed before swapping the list out, so a connect
                # racing with close() either lands in the swapped list (and
                # is closed there) or sees _closed here and self-closes —
                # never a leaked live reservation.
                with self._relay_socks_mu:
                    if self._closed.is_set():
                        sock.close()
                        return
                    self._relay_socks.append(sock)
                ts = str(int(time.time()))
                payload = f"{RELAY_RESERVE}|{self.peer_id}|{ts}".encode()
                sig = self.identity.sign(payload)
                send_json_frame(sock, {
                    "type": RELAY_RESERVE, "peer_id": self.peer_id, "ts": ts,
                    "sig": sig.hex(),
                })
                resp = recv_json_frame(sock)
                if not resp or not resp.get("ok"):
                    raise ConnectionError(f"reservation refused: {resp}")
                # Clear the connect timeout: this is a long-lived idle control
                # channel — a lingering per-socket timeout would make the
                # reservation flap every few seconds.
                sock.settimeout(None)
                log.info("reserved on relay %s", relay_addr)
                # PONGs and punch acks share the control socket with the
                # read loop's thread and punch threads; serialise sends.
                send_mu = threading.Lock()
                while not self._closed.is_set():
                    msg = recv_json_frame(sock)
                    if msg is None:
                        raise ConnectionError("relay control channel closed")
                    if msg.get("type") == RELAY_INCOMING:
                        threading.Thread(
                            target=self._accept_relayed,
                            args=(relay_addr, msg["conn_id"]), daemon=True,
                        ).start()
                    elif msg.get("type") == RELAY_PUNCH:
                        threading.Thread(
                            target=self._accept_punched,
                            args=(relay_addr, sock, send_mu, msg),
                            daemon=True,
                        ).start()
                    elif msg.get("type") == RELAY_PING:
                        with send_mu:
                            send_json_frame(sock, {"type": RELAY_PONG})
            except (OSError, ConnectionError, ValueError) as e:
                if sock is not None:
                    with self._relay_socks_mu:
                        if sock in self._relay_socks:
                            self._relay_socks.remove(sock)
                    try:
                        sock.close()
                    except OSError:
                        pass
                if self._closed.is_set():
                    return
                log.debug("relay control loop error (%s); retrying in %.0fs",
                          e, retry_interval)
                time.sleep(retry_interval)

    def _accept_punched(self, relay_addr: Multiaddr,
                        control_sock: socket.socket, send_mu: threading.Lock,
                        msg: dict) -> None:
        """Listener side of a hole punch: open a UDP socket, learn its
        observed endpoint, answer over the control channel, punch toward
        the dialer, and accept the normal inbound handshake over the
        reliable datagram layer (p2p/udp.py)."""
        from .udp import ReliableDgram, observe_udp_addr, punch

        try:
            dialer = (str(msg["udp_addr"][0]), int(msg["udp_addr"][1]))
        except (KeyError, TypeError, ValueError, IndexError):
            return
        usock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            usock.bind(("0.0.0.0", 0))
            observed = observe_udp_addr(usock, relay_addr.host,
                                        relay_addr.port, timeout=1.5,
                                        attempts=2)
            if observed is None:
                observed = usock.getsockname()
                if observed[0] in ("0.0.0.0", "::", ""):
                    # No routable endpoint to advertise: ack with null so
                    # the relay fails the dialer fast instead of letting
                    # it wait out the accept window.
                    with send_mu:
                        send_json_frame(control_sock, {
                            "type": RELAY_PUNCH_ACK,
                            "punch_id": msg.get("punch_id"),
                            "udp_addr": None,
                        })
                    usock.close()
                    return
            with send_mu:
                send_json_frame(control_sock, {
                    "type": RELAY_PUNCH_ACK,
                    "punch_id": msg.get("punch_id"),
                    "udp_addr": [observed[0], observed[1]],
                })
            punch(usock, dialer)
            self._handle_inbound(ReliableDgram(usock, dialer))
        except (OSError, ConnectionError, ValueError) as e:
            log.debug("punched accept failed: %s", e)
            try:
                usock.close()
            except OSError:
                pass

    def _accept_relayed(self, relay_addr: Multiaddr, conn_id: str) -> None:
        """Dial back to the relay to take an incoming circuit; the byte pipe
        then carries a normal inbound handshake."""
        try:
            sock = self._tcp_connect(relay_addr.host, relay_addr.port, 5.0)
            send_json_frame(sock, {"type": RELAY_ACCEPT, "conn_id": conn_id})
            resp = recv_json_frame(sock)
            if not resp or not resp.get("ok"):
                sock.close()
                return
            sock.settimeout(None)
            self._handle_inbound(sock)
        except (OSError, ConnectionError, ValueError) as e:
            log.debug("relayed accept failed: %s", e)
