"""Peer identity: Ed25519 keypairs and self-certifying peer IDs.

The reference generates an RSA-2048 key per node start (go/cmd/node/main.go:
293-299) and derives the libp2p peer ID from it. We use Ed25519 (faster
keygen/sign, 32-byte keys) and make the peer ID *self-certifying*: it embeds
the public key, so a dialer holding only a directory record can verify the
remote peer cryptographically. Identities can optionally be persisted —
the reference lists that as future work (README.md:134).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

try:
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )
    from cryptography.hazmat.primitives import serialization
    DEV_CRYPTO = False
except ImportError:
    # Containers without the cryptography package can opt in to the
    # INSECURE stdlib dev fallback (P2P_DEV_CRYPTO=1 — loopback dev and
    # loadgen scale-out only); anything else keeps the loud ImportError.
    from .devcrypto import require_dev_crypto
    require_dev_crypto("p2p.identity")
    from .devcrypto import (            # type: ignore[assignment]
        Ed25519PrivateKey,
        Ed25519PublicKey,
        serialization,
    )
    DEV_CRYPTO = True

from ..utils.base58 import b58decode, b58encode

# 2-byte tag prefixed to the raw public key before base58 encoding, giving
# peer IDs a stable leading character and versioning the key type. Dev
# fallback ids carry their own tag so a null-signature dev identity can
# never parse as — or verify against — a real Ed25519 peer id.
_ED25519_TAG = b"\x01\xdd" if DEV_CRYPTO else b"\x01\xed"


def public_key_to_peer_id(pub: Ed25519PublicKey) -> str:
    raw = pub.public_bytes(
        serialization.Encoding.Raw, serialization.PublicFormat.Raw
    )
    return b58encode(_ED25519_TAG + raw)


def peer_id_to_public_key(peer_id: str) -> Ed25519PublicKey:
    raw = b58decode(peer_id)
    if len(raw) != 34 or raw[:2] != _ED25519_TAG:
        raise ValueError(
            f"not an ed25519 peer id (this node runs "
            f"{'dev-crypto' if DEV_CRYPTO else 'real'} identities): "
            f"{peer_id!r}")
    return Ed25519PublicKey.from_public_bytes(raw[2:])


@dataclass
class Identity:
    private_key: Ed25519PrivateKey

    @classmethod
    def generate(cls) -> "Identity":
        return cls(Ed25519PrivateKey.generate())

    @classmethod
    def load_or_generate(cls, path: Optional[str]) -> "Identity":
        """Load a persisted identity from ``path``; generate (and persist,
        if a path is given) otherwise."""
        if path and os.path.exists(path):
            with open(path, "rb") as f:
                key = Ed25519PrivateKey.from_private_bytes(f.read())
            return cls(key)
        ident = cls.generate()
        if path:
            raw = ident.private_key.private_bytes(
                serialization.Encoding.Raw,
                serialization.PrivateFormat.Raw,
                serialization.NoEncryption(),
            )
            fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
            with os.fdopen(fd, "wb") as f:
                f.write(raw)
        return ident

    @property
    def public_key(self) -> Ed25519PublicKey:
        return self.private_key.public_key()

    @property
    def public_bytes(self) -> bytes:
        return self.public_key.public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )

    @property
    def peer_id(self) -> str:
        return public_key_to_peer_id(self.public_key)

    def sign(self, data: bytes) -> bytes:
        return self.private_key.sign(data)
