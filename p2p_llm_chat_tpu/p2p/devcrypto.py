"""DEV-ONLY stdlib stand-ins for the ``cryptography`` package.

The P2P plane's real primitives (Ed25519 identities, X25519 key
agreement, ChaCha20-Poly1305 streams — identity.py / transport.py /
dht.py) come from the ``cryptography`` package. Some containers — CI
images, the loadgen scale-out hosts — don't ship it, and the project
constraint is to gate missing deps, not install them. This module lets
the chat plane *function* there: every class mirrors the exact API
surface those modules import, built only on ``hashlib``/``hmac``/
``os.urandom``.

**THIS IS NOT CRYPTOGRAPHY.** The trade-offs, explicitly:

- "Ed25519" here is a null-signature scheme: sig = HMAC keyed by the
  *public* key, so anyone holding a peer id can forge. Structural
  contracts hold (32-byte keys, 64-byte sigs, deterministic verify,
  ``InvalidSignature`` on tamper) — authentication does not.
- "X25519" is 256-bit finite-field Diffie-Hellman (secp256k1's field
  prime, g=5): a real commutative key agreement, far below modern
  security margins.
- "ChaCha20Poly1305" is an HMAC-SHA256 keystream XOR with an
  encrypt-then-MAC tag: confidentiality against a passive reader of
  loopback traffic, nothing more.
- HKDF alone is the genuine RFC 5869 construction.

Because a dev deployment is interoperable only with itself, dev peer
ids carry their own version tag (identity.py switches ``_ED25519_TAG``)
so they can never be mistaken for — or verify against — real Ed25519
ids.

Opt-in is explicit: importing through :func:`require_dev_crypto` raises
ImportError unless ``P2P_DEV_CRYPTO=1`` is set, so a production node
missing its real dependency still fails loudly at boot instead of
silently downgrading to this.

Threading: every class here is immutable after construction (key
material only; per-call state is local) — audited for the round-13
lock-discipline sweep, so there is nothing to ``guarded-by``-annotate
and instances are safe to share across the transport's threads without
locks. Keep it that way: any future mutable cache added here must grow
a lock and the annotation.
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import os

from ..utils.env import env_bool

# secp256k1's field prime: a well-known 256-bit prime, so DH public
# values and shared secrets are exactly 32 bytes.
_DH_P = 2 ** 256 - 2 ** 32 - 977
_DH_G = 5


class InvalidSignature(Exception):
    """Mirror of ``cryptography.exceptions.InvalidSignature``."""


# AEAD decrypt failure; cryptography raises InvalidTag (a subclass of
# Exception) — callers here treat any decrypt exception as corruption.
class InvalidTag(Exception):
    pass


def require_dev_crypto(where: str) -> None:
    """Gate: raise ImportError unless the operator opted in.

    Called by identity/transport/dht when the real ``cryptography``
    import fails — the error message tells the operator both remedies.
    """
    if not env_bool("P2P_DEV_CRYPTO", False):
        raise ImportError(
            f"{where}: the 'cryptography' package is not installed. "
            "Install it for real P2P security, or set P2P_DEV_CRYPTO=1 "
            "to run the INSECURE stdlib dev fallback (loopback dev/"
            "loadgen only — see p2p/devcrypto.py)")


# ---------------------------------------------------------------------------
# serialization / hashes API shims (markers only — our key classes accept
# and ignore them, matching how the call sites use the real package)
# ---------------------------------------------------------------------------

class _Marker:
    def __init__(self, *a, **k) -> None:
        pass


class serialization:                                    # noqa: N801
    class Encoding:
        Raw = "raw"

    class PublicFormat:
        Raw = "raw"

    class PrivateFormat:
        Raw = "raw"

    class NoEncryption(_Marker):
        pass


class hashes:                                           # noqa: N801
    class SHA256(_Marker):
        digest_size = 32


# ---------------------------------------------------------------------------
# "Ed25519": null-signature identity keys (32-byte pub, 64-byte sig)
# ---------------------------------------------------------------------------

def _dev_sig(pub: bytes, data: bytes) -> bytes:
    h1 = _hmac.new(pub, b"devsig1" + data, hashlib.sha256).digest()
    h2 = _hmac.new(pub, b"devsig2" + data, hashlib.sha256).digest()
    return h1 + h2          # 64 bytes, the length transport.py frames


class Ed25519PublicKey:
    def __init__(self, raw: bytes) -> None:
        if len(raw) != 32:
            raise ValueError("dev public key must be 32 bytes")
        self._raw = raw

    @classmethod
    def from_public_bytes(cls, raw: bytes) -> "Ed25519PublicKey":
        return cls(raw)

    def public_bytes(self, *_a, **_k) -> bytes:
        return self._raw

    def verify(self, signature: bytes, data: bytes) -> None:
        if not _hmac.compare_digest(signature, _dev_sig(self._raw, data)):
            raise InvalidSignature("dev signature mismatch")


class Ed25519PrivateKey:
    def __init__(self, raw: bytes) -> None:
        if len(raw) != 32:
            raise ValueError("dev private key must be 32 bytes")
        self._raw = raw
        self._pub = hashlib.sha256(b"devpub" + raw).digest()

    @classmethod
    def generate(cls) -> "Ed25519PrivateKey":
        return cls(os.urandom(32))

    @classmethod
    def from_private_bytes(cls, raw: bytes) -> "Ed25519PrivateKey":
        return cls(raw)

    def private_bytes(self, *_a, **_k) -> bytes:
        return self._raw

    def public_key(self) -> Ed25519PublicKey:
        return Ed25519PublicKey(self._pub)

    def sign(self, data: bytes) -> bytes:
        return _dev_sig(self._pub, data)


# ---------------------------------------------------------------------------
# "X25519": 256-bit finite-field DH (commutative, 32-byte values)
# ---------------------------------------------------------------------------

class X25519PublicKey:
    def __init__(self, raw: bytes) -> None:
        if len(raw) != 32:
            raise ValueError("dev DH public value must be 32 bytes")
        self._raw = raw

    @classmethod
    def from_public_bytes(cls, raw: bytes) -> "X25519PublicKey":
        return cls(raw)

    def public_bytes(self, *_a, **_k) -> bytes:
        return self._raw


class X25519PrivateKey:
    def __init__(self, exp: int) -> None:
        self._exp = exp

    @classmethod
    def generate(cls) -> "X25519PrivateKey":
        # Exponent in [2, p-2]; 256 random bits are fine for a dev DH.
        return cls(2 + int.from_bytes(os.urandom(32), "big") % (_DH_P - 4))

    def public_key(self) -> X25519PublicKey:
        return X25519PublicKey(
            pow(_DH_G, self._exp, _DH_P).to_bytes(32, "big"))

    def exchange(self, peer: X25519PublicKey) -> bytes:
        val = int.from_bytes(peer.public_bytes(), "big")
        if not 2 <= val <= _DH_P - 2:
            raise ValueError("degenerate dev DH public value")
        return pow(val, self._exp, _DH_P).to_bytes(32, "big")


# ---------------------------------------------------------------------------
# HKDF (RFC 5869 — the one real construction here)
# ---------------------------------------------------------------------------

class HKDF:
    def __init__(self, algorithm=None, length: int = 32,
                 salt: bytes = b"", info: bytes = b"") -> None:
        self._length = length
        self._salt = salt or b"\x00" * 32
        self._info = info or b""

    def derive(self, ikm: bytes) -> bytes:
        prk = _hmac.new(self._salt, ikm, hashlib.sha256).digest()
        okm = b""
        t = b""
        block = 1
        while len(okm) < self._length:
            t = _hmac.new(prk, t + self._info + bytes([block]),
                          hashlib.sha256).digest()
            okm += t
            block += 1
        return okm[: self._length]


# ---------------------------------------------------------------------------
# "ChaCha20Poly1305": HMAC-keystream XOR + encrypt-then-MAC (16-byte tag)
# ---------------------------------------------------------------------------

class ChaCha20Poly1305:
    def __init__(self, key: bytes) -> None:
        if len(key) != 32:
            raise ValueError("dev AEAD key must be 32 bytes")
        self._key = key

    def _keystream(self, nonce: bytes, n: int) -> bytes:
        out = b""
        ctr = 0
        while len(out) < n:
            out += _hmac.new(self._key,
                             b"devks" + nonce + ctr.to_bytes(8, "big"),
                             hashlib.sha256).digest()
            ctr += 1
        return out[:n]

    def _tag(self, nonce: bytes, aad: bytes, ct: bytes) -> bytes:
        return _hmac.new(self._key, b"devtag" + nonce + aad + ct,
                         hashlib.sha256).digest()[:16]

    def encrypt(self, nonce: bytes, data: bytes, aad) -> bytes:
        ks = self._keystream(nonce, len(data))
        ct = bytes(a ^ b for a, b in zip(data, ks))
        return ct + self._tag(nonce, aad or b"", ct)

    def decrypt(self, nonce: bytes, data: bytes, aad) -> bytes:
        if len(data) < 16:
            raise InvalidTag("ciphertext shorter than tag")
        ct, tag = data[:-16], data[-16:]
        if not _hmac.compare_digest(tag, self._tag(nonce, aad or b"", ct)):
            raise InvalidTag("dev AEAD tag mismatch")
        ks = self._keystream(nonce, len(ct))
        return bytes(a ^ b for a, b in zip(ct, ks))
