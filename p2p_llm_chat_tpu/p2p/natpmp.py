"""NAT-PMP port mapping client (RFC 6886) — `libp2p.NATPortMap()` parity.

The reference enables router-cooperative port mapping on every node via
``libp2p.NATPortMap()`` (go/cmd/node/main.go:143): when the home gateway
speaks NAT-PMP/UPnP, the node maps its listen port and advertises the
external address, making itself directly dialable without a relay. This
module is the from-scratch equivalent for the common protocol (NAT-PMP;
its successor PCP shares the port and the result-code idea). Hole punching
(p2p/udp.py) remains the fallback when no cooperative gateway exists —
together they cover the reference's NATPortMap + DCUtR posture.

Protocol (RFC 6886, binary over UDP to gateway port 5351):

- external address request: ``ver=0 op=0`` (2 bytes) ->
  ``ver op+128 result(2) epoch(4) extip(4)`` (12 bytes)
- mapping request: ``ver=0 op={1:udp,2:tcp} rsvd(2) iport(2) eport(2)
  lifetime(4)`` (12 bytes) -> ``ver op+128 result(2) epoch(4) iport(2)
  eport(2) lifetime(4)`` (16 bytes)
- delete: a mapping request with lifetime 0 and eport 0
- retransmit: 250 ms initial RTO, doubling per try (RFC schedule; try
  count configurable — the RFC's 9 tries take ~64 s, too slow for a
  chat-node startup path, so the default here is 3)

Result codes: 0 success, 1 unsupported version, 2 not authorized,
3 network failure, 4 out of resources, 5 unsupported opcode.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from dataclasses import dataclass
from typing import Optional

from ..utils.log import get_logger

log = get_logger("natpmp")

NATPMP_PORT = 5351
_RESULT_NAMES = {
    0: "success",
    1: "unsupported version",
    2: "not authorized",
    3: "network failure",
    4: "out of resources",
    5: "unsupported opcode",
}

PROTO_UDP = 1
PROTO_TCP = 2


class NatPmpError(Exception):
    def __init__(self, result_code: int) -> None:
        self.result_code = result_code
        super().__init__(
            f"NAT-PMP result {result_code} "
            f"({_RESULT_NAMES.get(result_code, 'unknown')})")


class NatPmpUnavailable(Exception):
    """No gateway answered (not an error — most test/CI networks)."""


@dataclass
class Mapping:
    proto: int            # PROTO_UDP | PROTO_TCP
    internal_port: int
    external_port: int
    lifetime_s: int
    external_ip: Optional[str] = None


def discover_gateway() -> Optional[str]:
    """Default-route gateway from /proc/net/route (Linux). Returns None
    when there is no default route (e.g. isolated containers).

    Linux-only by design: on other platforms this returns None and
    NAT-PMP silently disables (the node falls back to hole punching /
    relay). Set ``NATPMP_GATEWAY`` explicitly to use NAT-PMP elsewhere.
    """
    try:
        with open("/proc/net/route") as f:
            next(f)  # header
            for line in f:
                parts = line.split()
                if len(parts) >= 3 and parts[1] == "00000000":
                    gw = int(parts[2], 16)
                    if gw == 0:
                        continue
                    # /proc encodes the address little-endian.
                    return socket.inet_ntoa(struct.pack("<I", gw))
    except (OSError, StopIteration, ValueError):
        pass
    return None


class NatPmpClient:
    """Blocking NAT-PMP client with the RFC retransmit schedule."""

    def __init__(self, gateway: str, port: int = NATPMP_PORT,
                 *, first_rto_s: float = 0.25, tries: int = 3) -> None:
        try:
            # Resolve once: the response filter compares source IPs, so a
            # hostname gateway would otherwise never match its own replies.
            self.gateway = socket.gethostbyname(gateway)
        except OSError:
            self.gateway = gateway   # fails cleanly in _transact
        self.port = port
        self.first_rto_s = first_rto_s
        self.tries = tries

    def _transact(self, req: bytes, want_op: int, resp_len: int) -> bytes:
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            rto = self.first_rto_s
            for _ in range(self.tries):
                sock.sendto(req, (self.gateway, self.port))
                deadline = time.monotonic() + rto
                while True:
                    rem = deadline - time.monotonic()
                    if rem <= 0:
                        break
                    sock.settimeout(rem)
                    try:
                        data, src = sock.recvfrom(64)
                    except socket.timeout:
                        break
                    except OSError:
                        break
                    # RFC 6886 §3.1: responses must come from the gateway.
                    if src[0] != self.gateway:
                        continue
                    if len(data) >= 4 and data[0] == 0 and data[1] == want_op:
                        result = struct.unpack("!H", data[2:4])[0]
                        if result != 0:
                            raise NatPmpError(result)
                        if len(data) >= resp_len:
                            return data
                rto *= 2          # RFC doubling schedule
            raise NatPmpUnavailable(
                f"no NAT-PMP response from {self.gateway}:{self.port}")
        finally:
            sock.close()

    def external_address(self) -> str:
        data = self._transact(struct.pack("!BB", 0, 0), 128, 12)
        return socket.inet_ntoa(data[8:12])

    def map_port(self, proto: int, internal_port: int,
                 external_port: int = 0, lifetime_s: int = 7200) -> Mapping:
        """Request a mapping; the gateway may assign a different external
        port than suggested (RFC 6886 §3.3) — always use the returned one."""
        req = struct.pack("!BBHHHI", 0, proto, 0, internal_port,
                          external_port, lifetime_s)
        data = self._transact(req, 128 + proto, 16)
        iport, eport, granted = struct.unpack("!HHI", data[8:16])
        if iport != internal_port:
            raise NatPmpUnavailable(
                f"response for wrong internal port {iport}")
        return Mapping(proto=proto, internal_port=internal_port,
                       external_port=eport, lifetime_s=granted)

    def unmap(self, proto: int, internal_port: int) -> None:
        """Delete our mapping (lifetime 0, external port 0, §3.4)."""
        req = struct.pack("!BBHHHI", 0, proto, 0, internal_port, 0, 0)
        try:
            self._transact(req, 128 + proto, 16)
        except (NatPmpError, NatPmpUnavailable) as e:
            log.debug("unmap %d/%d: %s", proto, internal_port, e)


class PortMapper:
    """Keeps one TCP mapping alive for a node's p2p listen port.

    ``acquire()`` discovers the gateway (or uses ``NATPMP_GATEWAY``),
    maps the port, and returns the external ``(ip, port)``;
    ``renew_if_due()`` re-requests at half-lifetime (RFC 6886 §3.3
    recommends renewing before expiry; the node calls it from its
    re-register loop); ``release()`` deletes the mapping on shutdown.
    Every failure degrades to "no mapping" — hole punching and the relay
    remain the fallback, matching the reference where NATPortMap is
    best-effort.
    """

    def __init__(self, internal_port: int, gateway: Optional[str] = None,
                 *, lifetime_s: int = 7200, port: int = NATPMP_PORT) -> None:
        self.internal_port = internal_port
        self.gateway = gateway if gateway is not None else discover_gateway()
        self.lifetime_s = lifetime_s
        self._gw_port = port
        self.mapping: Optional[Mapping] = None
        self._renew_at = 0.0
        # Orders renew against release: a renew in flight when release()
        # fires would otherwise RE-create the mapping after the delete,
        # leaking the port forward shutdown cleanup exists to prevent.
        self._mu = threading.Lock()
        self._released = False

    def acquire(self) -> Optional[tuple[str, int]]:
        if self.gateway is None:
            log.info("NAT-PMP: no default gateway; skipping")
            return None
        client = NatPmpClient(self.gateway, self._gw_port)
        try:
            ext_ip = client.external_address()
            m = client.map_port(PROTO_TCP, self.internal_port,
                                self.internal_port, self.lifetime_s)
        except (NatPmpError, NatPmpUnavailable) as e:
            log.info("NAT-PMP unavailable (%s); relying on punch/relay", e)
            return None
        m.external_ip = ext_ip
        self.mapping = m
        self._renew_at = time.monotonic() + m.lifetime_s / 2
        log.info("NAT-PMP mapped %s:%d -> :%d (lifetime %ds)",
                 ext_ip, m.external_port, m.internal_port, m.lifetime_s)
        return ext_ip, m.external_port

    def renew_if_due(self) -> Optional[tuple[str, int]]:
        """Renew at half-lifetime. Returns the new external ``(ip, port)``
        when it CHANGED (gateway reboot / reassigned port — RFC 6886 §3.3
        allows a different grant; §3.6's epoch exists for exactly this),
        else None. Callers must re-advertise on change."""
        with self._mu:
            if (self._released or self.mapping is None
                    or time.monotonic() < self._renew_at):
                return None
            prev = (self.mapping.external_ip, self.mapping.external_port)
            # Fewer retransmits than the initial map: renew runs under
            # self._mu, which node.stop() -> release() also takes, so the
            # worst-case blocking window here directly delays shutdown
            # (ADVICE r4). A missed renew retries at lifetime/4 anyway.
            client = NatPmpClient(self.gateway, self._gw_port, tries=2)
            try:
                ext_ip = client.external_address()
                m = client.map_port(PROTO_TCP, self.internal_port,
                                    self.mapping.external_port,
                                    self.lifetime_s)
                m.external_ip = ext_ip
                self.mapping = m
                self._renew_at = time.monotonic() + m.lifetime_s / 2
                cur = (ext_ip, m.external_port)
                return cur if cur != prev else None
            except (NatPmpError, NatPmpUnavailable) as e:
                log.warning("NAT-PMP renew failed (%s); mapping may lapse", e)
                # Back off half a lifetime before retrying.
                self._renew_at = time.monotonic() + self.lifetime_s / 4
                return None

    def release(self) -> None:
        # Takes the same lock as renew_if_due, so an in-flight renew
        # finishes first and the delete below is the LAST gateway write.
        with self._mu:
            self._released = True
            if self.mapping is not None and self.gateway is not None:
                NatPmpClient(self.gateway, self._gw_port).unmap(
                    PROTO_TCP, self.internal_port)
                self.mapping = None
