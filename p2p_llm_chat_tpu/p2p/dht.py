"""Kademlia DHT: serverless username -> signed peer-record resolution.

The reference constructs a kad-DHT on every node (go/cmd/node/main.go:151,
via go-libp2p-kad-dht v0.34.0, go/cmd/node/go.mod:9) but never routes with
it — discovery is 100% via the Directory service (SURVEY.md §2), and DHT
errors are non-fatal (main.go:153). Here the DHT is built from scratch AND
actually wired in: it is the third rung of the node's lookup ladder
(directory -> lookup cache -> DHT), so two peers whose bootstrap graphs
overlap can resolve each other with the directory fully down — including
peers that have never talked (which the cache rung cannot cover).

Design (classic Kademlia, adapted to the chat plane):

- Node IDs are 256-bit: sha256 of the self-certifying base58 peer id
  (p2p/identity.py). Record keys are sha256(b"user:" + username), so the
  username namespace and the node-ID space share one XOR metric.
- RPCs are single JSON datagrams over the node's UDP socket — PING,
  FIND_NODE, GET, PUT. Request/response with per-RPC nonces and small
  bounded retries; Kademlia tolerates loss by design, so the reliable
  stream machinery (p2p/udp.py) is deliberately not used here.
- Every datagram is SIGNED by its sender's Ed25519 key over the canonical
  message body, verified against the key embedded in the claimed peer id;
  unverifiable datagrams are dropped. Routing-table updates are further
  PROOF-GATED (S/Kademlia-style): a response proves key ownership against
  OUR fresh nonce, so it may add/move a contact directly; a request only
  triggers a background challenge ping to the observed source address,
  and the table changes when (and only when) the signed pong comes back.
  Without this, one spoofed ``{"from": victim}`` datagram would re-point
  the victim's routing entry at an attacker address (contact hijack /
  record eclipse).
- Records are SIGNED: {username, peer_id, addrs, seq} with an Ed25519
  signature over the canonical JSON by the key embedded in peer_id.
  Storers validate (a) the signature against the self-certifying id and
  (b) that the record key matches its username, so a malicious node
  cannot alter another IDENTITY's record or file a record under the
  wrong key; seq is last-writer-wins (directory.py parity) and stale
  writes are ignored. The username -> identity binding itself is
  last-writer-wins, exactly the reference directory's trust model (its
  /register is unauthenticated, go/cmd/directory/main.go — README.md:135
  treats the directory as trusted infrastructure): a squatter CAN claim
  a username with their own identity here just as they can there. What
  the signatures add over the directory: third-party DHT nodes cannot
  tamper with records in flight or in storage, and node.py pins the
  peer IDENTITY for warm pairs (a DHT record for a known peer is only
  accepted if its peer_id matches the cached binding).
- k-buckets (k=16) with least-recently-seen eviction: a full bucket pings
  its oldest contact and only replaces it if the ping fails (the classic
  liveness bias that keeps long-lived contacts).
- Iterative (not recursive) lookups with alpha=3 parallelism; PUT stores
  on the k closest nodes found; GET returns the freshest (highest-seq)
  valid record seen. Stored records expire after ``record_ttl_s`` (2h);
  owners republish on the node's re-register interval (node.py).
"""

from __future__ import annotations

import functools
import hashlib
import json
import secrets
import socket
import threading
import time
from concurrent.futures import ThreadPoolExecutor, TimeoutError as FutTimeout, as_completed
from dataclasses import dataclass, field
from typing import Callable, Optional

try:
    from cryptography.exceptions import InvalidSignature
except ImportError:
    # Gated stdlib dev fallback (P2P_DEV_CRYPTO=1): identity.py resolves
    # the same way, and its dev verify raises this class.
    from .devcrypto import require_dev_crypto
    require_dev_crypto("p2p.dht")
    from .devcrypto import InvalidSignature     # type: ignore[assignment]

from .identity import Identity, peer_id_to_public_key
from ..utils.backoff import Backoff, note_retry
from ..utils.failpoints import failpoint
from ..utils.log import get_logger

log = get_logger("dht")

K = 16            # bucket size / replication factor
ALPHA = 3         # lookup parallelism
ID_BITS = 256
_MAX_DGRAM = 8192


def node_id_for_peer(peer_id: str) -> int:
    return int.from_bytes(hashlib.sha256(peer_id.encode()).digest(), "big")


def key_for_username(username: str) -> int:
    return int.from_bytes(
        hashlib.sha256(b"user:" + username.encode()).digest(), "big")


def _distance(a: int, b: int) -> int:
    return a ^ b


@dataclass(frozen=True)
class Contact:
    peer_id: str
    host: str
    port: int

    @functools.cached_property
    def node_id(self) -> int:
        # cached: lookups sort shortlists by distance every round, and
        # re-hashing the same peer id per comparison adds up.
        return node_id_for_peer(self.peer_id)

    def to_wire(self) -> dict:
        return {"peer_id": self.peer_id, "host": self.host, "port": self.port}

    @classmethod
    def from_wire(cls, d: dict) -> "Contact":
        return cls(peer_id=str(d["peer_id"]), host=str(d["host"]),
                   port=int(d["port"]))


def _msg_signing_bytes(msg: dict) -> bytes:
    """Canonical bytes of a wire message minus its signature field."""
    core = {k: v for k, v in msg.items() if k != "sig"}
    return json.dumps(core, sort_keys=True, separators=(",", ":")).encode()


def _verify_msg(msg: dict) -> bool:
    """Signature valid against the key embedded in the claimed peer id."""
    pid = msg.get("from")
    sig = msg.get("sig")
    if not isinstance(pid, str) or not isinstance(sig, str):
        return False
    try:
        pub = peer_id_to_public_key(pid)
        pub.verify(bytes.fromhex(sig), _msg_signing_bytes(msg))
        return True
    except (InvalidSignature, ValueError):
        return False


def _record_signing_bytes(username: str, peer_id: str, addrs: list[str],
                          seq: int) -> bytes:
    # Canonical JSON: sorted keys, no whitespace — both signer and verifier
    # rebuild this exact byte string.
    return json.dumps(
        {"addrs": addrs, "peer_id": peer_id, "seq": seq, "username": username},
        sort_keys=True, separators=(",", ":")).encode()


@dataclass
class SignedRecord:
    """A username's signed address record (the DHT's stored value)."""
    username: str
    peer_id: str
    addrs: list[str]
    seq: int
    sig_hex: str
    stored_at: float = field(default_factory=time.monotonic, compare=False)

    @classmethod
    def create(cls, ident: Identity, username: str, addrs: list[str],
               seq: Optional[int] = None) -> "SignedRecord":
        seq = int(time.time() * 1000) if seq is None else seq
        sig = ident.sign(_record_signing_bytes(username, ident.peer_id,
                                               list(addrs), seq))
        return cls(username=username, peer_id=ident.peer_id,
                   addrs=list(addrs), seq=seq, sig_hex=sig.hex())

    def verify(self, expect_key: Optional[int] = None) -> bool:
        """Signature valid against the self-certifying peer id, and (when
        ``expect_key`` is given) the record actually belongs at that key."""
        if expect_key is not None and key_for_username(self.username) != expect_key:
            return False
        try:
            pub = peer_id_to_public_key(self.peer_id)
            pub.verify(bytes.fromhex(self.sig_hex),
                       _record_signing_bytes(self.username, self.peer_id,
                                             self.addrs, self.seq))
            return True
        except (InvalidSignature, ValueError):
            return False

    def to_wire(self) -> dict:
        return {"username": self.username, "peer_id": self.peer_id,
                "addrs": self.addrs, "seq": self.seq, "sig": self.sig_hex}

    @classmethod
    def from_wire(cls, d: dict) -> "SignedRecord":
        return cls(username=str(d["username"]), peer_id=str(d["peer_id"]),
                   addrs=[str(a) for a in d["addrs"]], seq=int(d["seq"]),
                   sig_hex=str(d["sig"]))


class RoutingTable:
    """256 k-buckets ordered by shared-prefix length with ``self_id``.

    Thread-safe; contacts move to the tail (most recently seen) on every
    touch. When a bucket is full, ``maybe_add`` returns the least-recently
    seen contact as an eviction CANDIDATE — the caller pings it and calls
    ``replace`` only if the ping fails (Kademlia's liveness bias).
    """

    def __init__(self, self_id: int, k: int = K) -> None:
        self.self_id = self_id
        self.k = k
        self._buckets: list[list[Contact]] = [[] for _ in range(ID_BITS)]  # guarded-by: _mu
        self._mu = threading.Lock()

    def _bucket_index(self, node_id: int) -> int:
        d = _distance(self.self_id, node_id)
        return d.bit_length() - 1 if d else 0

    def touch(self, c: Contact) -> Optional[Contact]:
        """Record contact activity. Returns an eviction candidate when the
        bucket is full (see class docstring), else None."""
        if c.node_id == self.self_id:
            return None
        with self._mu:
            bucket = self._buckets[self._bucket_index(c.node_id)]
            for i, existing in enumerate(bucket):
                if existing.peer_id == c.peer_id:
                    bucket.pop(i)
                    bucket.append(c)   # refresh addr + recency
                    return None
            if len(bucket) < self.k:
                bucket.append(c)
                return None
            return bucket[0]

    def replace(self, stale: Contact, fresh: Contact) -> None:
        with self._mu:
            bucket = self._buckets[self._bucket_index(stale.node_id)]
            for i, existing in enumerate(bucket):
                if existing.peer_id == stale.peer_id:
                    bucket.pop(i)
                    break
            if (len(bucket) < self.k
                    and all(e.peer_id != fresh.peer_id for e in bucket)):
                bucket.append(fresh)

    def get(self, peer_id: str) -> Optional[Contact]:
        # The contact's bucket is derivable from its id — no full scan
        # (this runs on the rx thread for every request datagram).
        with self._mu:
            bucket = self._buckets[self._bucket_index(node_id_for_peer(peer_id))]
            for existing in bucket:
                if existing.peer_id == peer_id:
                    return existing
        return None

    def remove(self, peer_id: str) -> None:
        with self._mu:
            bucket = self._buckets[self._bucket_index(node_id_for_peer(peer_id))]
            for i, existing in enumerate(bucket):
                if existing.peer_id == peer_id:
                    bucket.pop(i)
                    return

    def closest(self, target: int, n: Optional[int] = None) -> list[Contact]:
        n = self.k if n is None else n
        with self._mu:
            allc = [c for b in self._buckets for c in b]
        allc.sort(key=lambda c: _distance(c.node_id, target))
        return allc[:n]

    def __len__(self) -> int:
        with self._mu:
            return sum(len(b) for b in self._buckets)


class DHTNode:
    """One Kademlia participant bound to a UDP socket.

    ``start()`` spawns the receiver thread; ``bootstrap(addrs)`` joins the
    network via any known (host, port) seeds; ``put_record``/``get_record``
    are the username-record surface node.py uses. All RPCs are fire-and-
    retry datagrams — an unreachable peer just times out its slot in the
    iterative lookup.
    """

    def __init__(self, ident: Identity, listen_addr: str = "127.0.0.1:0",
                 *, k: int = K, rpc_timeout_s: float = 0.6,
                 record_ttl_s: float = 7200.0,
                 max_records: int = 4096) -> None:
        self.ident = ident
        self.node_id = node_id_for_peer(ident.peer_id)
        self.k = k
        self.rpc_timeout_s = rpc_timeout_s
        self.record_ttl_s = record_ttl_s
        self.max_records = max_records
        host, _, port = listen_addr.rpartition(":")
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind((host or "127.0.0.1", int(port or 0)))
        self.table = RoutingTable(self.node_id, k=k)
        self._store: dict[int, SignedRecord] = {}  # guarded-by: _store_mu
        self._store_mu = threading.Lock()
        # rid -> (event, hits, resolved dst addr the RPC was sent to)
        self._pending: dict[str, tuple[threading.Event, list,
                                       tuple[str, int]]] = {}  # guarded-by: _pending_mu
        self._pending_mu = threading.Lock()
        self._evicting: set[str] = set()  # guarded-by: _evict_mu
        self._evict_mu = threading.Lock()
        self._challenging: set[str] = set()  # guarded-by: _challenge_mu
        self._challenge_mu = threading.Lock()
        # Destination-resolution memo (_resolve_dst): hostname -> IP, so
        # a slow DNS server is consulted once per destination, not on
        # every RPC. Bounded; numeric IPs never enter it.
        self._resolve_cache: dict[str, str] = {}  # guarded-by: _resolve_mu
        self._resolve_mu = threading.Lock()
        self._closed = threading.Event()
        self._rx: Optional[threading.Thread] = None
        # One long-lived pool for lookup/store fan-out — per-round executor
        # creation on the inline /send path would pay thread startup for
        # every ALPHA-batch and leak straggler threads per round. Sized at
        # 3x the widest single fan-out (k) so a /send-path lookup does not
        # queue behind a concurrent republish's k store RPCs and time out
        # live contacts as false no-answers.
        self._pool = ThreadPoolExecutor(max_workers=3 * max(k, ALPHA),
                                        thread_name_prefix="dht-fan")

    # -- lifecycle -----------------------------------------------------------

    @property
    def addr(self) -> tuple[str, int]:
        return self.sock.getsockname()

    def start(self) -> "DHTNode":
        self._rx = threading.Thread(target=self._recv_loop, daemon=True,
                                    name="dht-rx")
        self._rx.start()
        return self

    def close(self) -> None:
        self._closed.set()
        self._pool.shutdown(wait=False)
        try:
            self.sock.close()
        except OSError:
            pass

    def bootstrap(self, seeds: list[tuple[str, int]]) -> int:
        """Ping the seeds, then iteratively look up our own id to populate
        buckets along the path (the standard Kademlia join). Returns the
        routing-table size; 0 means nobody answered (non-fatal, matching
        the reference's non-fatal DHT errors, main.go:153)."""
        for host, port in seeds:
            self._rpc({"t": "ping"}, (host, port))
        self.iterative_find_node(self.node_id)
        return len(self.table)

    # -- wire ----------------------------------------------------------------

    def _recv_loop(self) -> None:
        import errno as _errno

        while not self._closed.is_set():
            try:
                data, src = self.sock.recvfrom(_MAX_DGRAM)
            except OSError as e:
                # Transient errors (e.g. ICMP port-unreachable surfacing as
                # ConnectionResetError on some stacks) must not kill the rx
                # thread — only a real close should end the loop. An fd
                # invalidated without _closed being set (EBADF/ENOTSOCK)
                # is unrecoverable: exit instead of busy-spinning, and a
                # short sleep paces any other persistent error state
                # (ADVICE r4).
                if self._closed.is_set():
                    return
                if e.errno in (_errno.EBADF, _errno.ENOTSOCK):
                    log.warning("dht rx socket invalidated (%s); rx "
                                "thread exiting", e)
                    return
                time.sleep(0.01)
                continue
            try:
                msg = json.loads(data.decode())
            except (ValueError, UnicodeDecodeError):
                continue
            try:
                self._on_message(msg, src)
            except Exception as e:  # noqa: BLE001 — one bad dgram must not kill rx
                log.warning("dht rx error from %s: %s", src, e)

    def _on_message(self, msg: dict, src: tuple[str, int]) -> None:
        t = msg.get("t")
        rid = msg.get("rid")
        sender_pid = msg.get("from")
        if not isinstance(sender_pid, str) or not sender_pid:
            return
        if sender_pid == self.ident.peer_id or not _verify_msg(msg):
            return                       # unsigned/forged: drop entirely
        if t in ("pong", "nodes", "value", "stored"):
            with self._pending_mu:
                ent = self._pending.get(rid) if isinstance(rid, str) else None
            if ent is not None:
                # A signed response to OUR nonce proves the key holder is
                # reachable at src — the only path that updates the table
                # directly. (The reply address IS the contact address:
                # single-socket UDP.) Table update only when src matches
                # the address the RPC was SENT to: a challenged peer that
                # can spoof UDP sources must not re-point its own contact
                # entry at a victim address (ADVICE r4 reflection vector).
                # The response itself still delivers either way — rid
                # possession plus the signature prove it's the peer we
                # asked.
                if src == ent[2]:
                    self._note_contact(Contact(sender_pid, src[0], src[1]))
                ent[1].append((msg, src))
                ent[0].set()
            return
        # Requests never touch the table on their own say-so: challenge the
        # claimed identity at the observed source address in the background
        # (the signed pong lands in the response path above).
        known = self.table.get(sender_pid)
        if known is None or (known.host, known.port) != src:
            self._challenge(src)
        else:
            self.table.touch(known)      # recency refresh, address unchanged
        reply = {"rid": rid, "from": self.ident.peer_id}
        if t == "ping":
            reply["t"] = "pong"
        elif t == "find_node":
            reply["t"] = "nodes"
            reply["nodes"] = [c.to_wire()
                              for c in self.table.closest(int(msg["target"], 16))]
        elif t == "get":
            key = int(msg["key"], 16)
            rec = self._load(key)
            if rec is not None:
                reply["t"] = "value"
                reply["record"] = rec.to_wire()
            else:
                reply["t"] = "nodes"
                reply["nodes"] = [c.to_wire() for c in self.table.closest(key)]
        elif t == "put":
            ok = self._maybe_store(SignedRecord.from_wire(msg["record"]))
            reply["t"] = "stored"
            reply["ok"] = ok
        else:
            return
        self._send(reply, src)

    def _send(self, msg: dict, dst: tuple[str, int]) -> None:
        msg["sig"] = self.ident.sign(_msg_signing_bytes(msg)).hex()
        try:
            self.sock.sendto(json.dumps(msg).encode(), dst)
        except OSError:
            pass

    def _rpc(self, msg: dict, dst: tuple[str, int],
             timeout_s: Optional[float] = None, attempts: int = 2,
             ) -> Optional[dict]:
        """Request -> first matching response; one bounded retry (plain UDP:
        a single lost datagram must not read as a dead peer)."""
        rid = secrets.token_hex(8)
        msg = dict(msg, rid=rid, **{"from": self.ident.peer_id})
        ev = threading.Event()
        hits: list = []
        # dst rides the entry so the response path can require the reply
        # to come from the address we actually queried before it may
        # update the routing table. Hostname dsts resolve first
        # (_resolve_dst — numeric-IP fast path, memoized DNS): recvfrom
        # reports the numeric source IP, so a literal hostname tuple
        # would never match its own replies and seed bootstrap
        # (DHT_BOOTSTRAP=host:port) would silently never table the seed.
        # (A multihomed peer replying from a different interface IP is
        # still skipped for the table update — the response itself
        # delivers; the peer enters the table on a later direct answer.)
        # Resolution happens BEFORE taking _pending_mu: the RX thread
        # needs that lock to dispatch every response, so a blocking
        # gethostbyname inside it would stall the whole node's response
        # path for the resolver timeout.
        dst_ip = self._resolve_dst(dst[0])
        with self._pending_mu:
            self._pending[rid] = (ev, hits, (dst_ip, dst[1]))
        try:
            per_try = self.rpc_timeout_s if timeout_s is None else timeout_s
            # Jittered backoff between retries (utils/backoff): every
            # node retrying a just-restarted seed at the same instant is
            # a thundering herd; the jitter decorrelates them. Bounded:
            # the extra sleep stays well under one rpc timeout, so the
            # lookup wall budgets (_iterate / the /send handler) hold.
            bo = Backoff(base_s=per_try / 8, max_s=per_try / 2, jitter=0.5)
            for i in range(max(1, attempts)):
                # Failpoint: one RPC attempt. ``drop`` = this datagram is
                # lost on the wire (the caller sees a timeout-shaped None
                # without waiting out the real timeout — fast chaos);
                # ``delay`` injects network latency before the send.
                act = failpoint("p2p.dht.rpc")
                if act is not None and act.kind == "drop":
                    if i + 1 >= max(1, attempts):
                        return None
                    continue       # counted by the follow-up attempt below
                if i > 0:
                    note_retry()
                    time.sleep(bo.next())
                self._send(dict(msg), dst)
                if ev.wait(per_try):
                    return hits[0][0]
            return None
        finally:
            with self._pending_mu:
                self._pending.pop(rid, None)

    def _resolve_dst(self, host: str) -> str:
        """Destination IP for the response-address match (see _rpc).

        Numeric IPv4 literals — the overwhelmingly common case: every
        contact learned from the wire already carries one — pass through
        on an ``inet_aton`` probe without ever touching the resolver;
        only operator-supplied bootstrap HOSTNAMES resolve, and each
        resolves once per node lifetime (memoized) so a slow or dead DNS
        server cannot stall every RPC behind a synchronous
        ``gethostbyname``. Resolution FAILURES are not memoized: DNS
        flakiness at boot must not pin a hostname to itself forever —
        the next RPC retries. Staleness trade-off: a re-pointed
        bootstrap hostname is not picked up until restart; bootstrap
        seeds are static operator config, and the cost of the
        alternative was a resolver call on the hot path of every RPC."""
        try:
            # Normalized via ntoa, not returned verbatim: inet_aton also
            # accepts abbreviated forms ('127.1', '10.1.2') that would
            # never equal recvfrom's canonical source IP — the response
            # match would then silently skip tabling the peer.
            return socket.inet_ntoa(socket.inet_aton(host))
        except OSError:
            pass
        with self._resolve_mu:
            ip = self._resolve_cache.get(host)
        if ip is not None:
            return ip
        try:
            ip = socket.gethostbyname(host)
        except OSError:
            return host                       # transient: retry next RPC
        with self._resolve_mu:
            if len(self._resolve_cache) >= 256:
                self._resolve_cache.clear()   # bounded, rebuilds on use
            self._resolve_cache[host] = ip
        return ip

    # -- routing-table maintenance -------------------------------------------

    def _challenge(self, src: tuple[str, int]) -> None:
        """Background ping of an unproven requester's source address; the
        signed pong (if any) enters the table via the response path."""
        key = "%s:%d" % src
        with self._challenge_mu:
            if key in self._challenging or len(self._challenging) >= 64:
                # Cap outstanding challenges: identities are free to mint,
                # so unbounded per-datagram thread spawn would be a cheaper
                # DoS than the hijack this defends against. At the cap new
                # (possibly legit) requesters are simply not tabled yet —
                # they retry on their next RPC.
                return
            self._challenging.add(key)

        def _go() -> None:
            try:
                self._rpc({"t": "ping"}, src)
            finally:
                with self._challenge_mu:
                    self._challenging.discard(key)

        threading.Thread(target=_go, daemon=True,
                         name="dht-challenge").start()

    def _note_contact(self, c: Contact) -> None:
        candidate = self.table.touch(c)
        if candidate is None:
            return
        # Full bucket: keep the old contact iff it still answers. The ping
        # MUST leave the rx thread — _note_contact runs on it, and the rx
        # thread is the only reader that could ever deliver the pong (a
        # same-thread _rpc would always time out, evicting live contacts
        # and stalling all datagram processing for rpc_timeout_s).
        with self._evict_mu:
            if candidate.peer_id in self._evicting:
                return
            self._evicting.add(candidate.peer_id)

        def _check() -> None:
            try:
                if self._rpc({"t": "ping"},
                             (candidate.host, candidate.port)) is None:
                    self.table.replace(candidate, c)
            finally:
                with self._evict_mu:
                    self._evicting.discard(candidate.peer_id)

        threading.Thread(target=_check, daemon=True,
                         name="dht-evict-check").start()

    # -- store ---------------------------------------------------------------

    def _maybe_store(self, rec: SignedRecord) -> bool:
        key = key_for_username(rec.username)
        if not rec.verify(expect_key=key):
            log.warning("dht: rejecting unverifiable record for %r",
                        rec.username)
            return False
        with self._store_mu:
            cur = self._store.get(key)
            if cur is not None and cur.seq > rec.seq:
                return False       # stale write (last-writer-wins, by seq)
            if cur is None and len(self._store) >= self.max_records:
                # Bound the store (anyone can mint identities and PUT):
                # sweep expired entries, then evict the key FARTHEST from
                # our node id — Kademlia stores keys near their closest
                # nodes, so the farthest record is the one some other node
                # is responsible for.
                now = time.monotonic()
                for k2 in [k2 for k2, r in self._store.items()
                           if now - r.stored_at > self.record_ttl_s]:
                    del self._store[k2]
                if len(self._store) >= self.max_records:
                    victim = max(self._store,
                                 key=lambda k2: _distance(k2, self.node_id))
                    if _distance(key, self.node_id) >= _distance(
                            victim, self.node_id):
                        return False   # new key is the farthest — refuse
                    del self._store[victim]
            self._store[key] = rec
        return True

    def _load(self, key: int) -> Optional[SignedRecord]:
        with self._store_mu:
            rec = self._store.get(key)
            if rec is None:
                return None
            if time.monotonic() - rec.stored_at > self.record_ttl_s:
                del self._store[key]
                return None
            return rec

    def _suspect(self, c: Contact) -> None:
        """A contact missed a lookup RPC: evict only after a dedicated ping
        also fails (deduped, off-thread). If it answers, the signed-pong
        path refreshes its recency instead."""
        with self._challenge_mu:
            key = "suspect:" + c.peer_id
            if key in self._challenging or len(self._challenging) >= 64:
                return
            self._challenging.add(key)

        def _go() -> None:
            try:
                if self._rpc({"t": "ping"}, (c.host, c.port)) is None:
                    self.table.remove(c.peer_id)
            finally:
                with self._challenge_mu:
                    self._challenging.discard(key)

        threading.Thread(target=_go, daemon=True, name="dht-suspect").start()

    # -- iterative lookups ---------------------------------------------------

    def _fan_out(self, contacts: list[Contact],
                 fn: Callable[[Contact], object],
                 max_wait_s: Optional[float] = None) -> dict[Contact, object]:
        """Run ``fn`` over contacts on the shared pool; drop stragglers and
        raised calls (a missing key = no answer). Bounded: fn is an _rpc
        wrapper, itself capped at attempts*timeout; ``max_wait_s``
        additionally clamps the collect window (lookup deadlines)."""
        if not contacts:
            return {}
        out: dict[Contact, object] = {}
        try:
            futs = {self._pool.submit(fn, c): c for c in contacts}
        except RuntimeError:      # pool shut down: node closing
            return {}
        wait = 2 * self.rpc_timeout_s + 0.5
        if max_wait_s is not None:
            wait = min(wait, max_wait_s)
        try:
            for f in as_completed(futs, timeout=wait):
                try:
                    out[futs[f]] = f.result()
                except Exception:  # noqa: BLE001 — treat as no answer
                    pass
        except FutTimeout:
            pass
        for f in futs:
            f.cancel()   # drop any still-queued RPCs nobody will read
        return out

    def _iterate(self, target: int,
                 query: Callable[[Contact],
                                 Optional[tuple[Optional[SignedRecord],
                                                list[Contact]]]],
                 deadline: Optional[float] = None,
                 ) -> tuple[Optional[SignedRecord], list[Contact]]:
        """Shared iterative-lookup core: keep querying the alpha closest
        unqueried candidates until the k closest are all queried or a value
        surfaces. ``query`` returns None when the peer gave NO answer (the
        suspect/eviction path) vs ``(record_or_None, contacts)`` for any
        answer. Returns (best_record_or_None, k closest live contacts).
        ``deadline`` (time.monotonic()) bounds total wall time: a table
        full of dead contacts otherwise costs multiple alpha-rounds of
        UDP timeouts (ADVICE r4 — the /send handler runs this inline)."""
        shortlist: dict[str, Contact] = {
            c.peer_id: c for c in self.table.closest(target, self.k)}
        queried: set[str] = set()
        best: Optional[SignedRecord] = None
        while True:
            ordered = sorted(shortlist.values(),
                             key=lambda c: _distance(c.node_id, target))
            batch = [c for c in ordered[:self.k]
                     if c.peer_id not in queried][:ALPHA]
            past = (deadline is not None
                    and time.monotonic() >= deadline)
            if past or not batch:
                live = [c for c in ordered if c.peer_id in queried]
                return best, live[:self.k]
            # Clamp the round's collect window to the remaining budget:
            # without this, a deadline that lands mid-round still waits
            # _fan_out's full as_completed timeout (~1.7 s) past it.
            wait = None
            if deadline is not None:
                wait = max(0.05, deadline - time.monotonic())
            results = self._fan_out(batch, query, max_wait_s=wait)
            for c in batch:
                queried.add(c.peer_id)
                res = results.get(c)
                if res is None:
                    # No answer (query returns None on RPC timeout, never
                    # an empty tuple): out of this lookup, but NOT out of
                    # the routing table directly — a dedicated background
                    # ping decides eviction (one miss under bursty loss
                    # must not strip live long-lived contacts; the
                    # docstring's liveness bias).
                    shortlist.pop(c.peer_id, None)
                    self._suspect(c)
                    continue
                rec, nodes = res
                if rec is not None and (best is None or rec.seq > best.seq):
                    best = rec
                for nc in nodes:
                    if nc.peer_id != self.ident.peer_id:
                        shortlist.setdefault(nc.peer_id, nc)
            if best is not None:
                # FIND_VALUE terminates on the first verified value — the
                # /send path calls this inline, and walking the rest of the
                # shortlist would add seconds of UDP timeouts for nothing.
                ordered = sorted(shortlist.values(),
                                 key=lambda c: _distance(c.node_id, target))
                live = [c for c in ordered if c.peer_id in queried]
                return best, live[:self.k]

    def iterative_find_node(self, target: int) -> list[Contact]:
        def q(c: Contact) -> Optional[tuple[None, list[Contact]]]:
            resp = self._rpc({"t": "find_node", "target": f"{target:064x}"},
                             (c.host, c.port))
            if resp is None:
                return None            # no answer -> suspect path
            if resp.get("t") != "nodes":
                return (None, [])      # answered, just not useful
            return None, [Contact.from_wire(d) for d in resp.get("nodes", [])]
        _, closest = self._iterate(target, q)
        return closest

    def put_record(self, rec: SignedRecord) -> int:
        """Store ``rec`` on the k closest nodes to its key (and locally if
        we are one of them). Returns the number of stores acknowledged."""
        key = key_for_username(rec.username)
        closest = self.iterative_find_node(key)
        self._maybe_store(rec)
        # Parallel stores: serial dead-contact timeouts would stack to
        # ~10s+ on the re-register thread after churn.
        results = self._fan_out(
            closest[:self.k],
            lambda c: self._rpc({"t": "put", "record": rec.to_wire()},
                                (c.host, c.port)))
        return sum(1 for resp in results.values()
                   if resp is not None and resp.get("ok"))

    def get_record(self, username: str,
                   budget_s: Optional[float] = None) -> Optional[SignedRecord]:
        """Iterative value lookup; validates locally before returning (a
        malicious responder cannot shortcut the signature check).
        ``budget_s`` caps total lookup wall time (see _iterate)."""
        key = key_for_username(username)
        local = self._load(key)

        def q(c: Contact) -> Optional[tuple[Optional[SignedRecord],
                                            list[Contact]]]:
            resp = self._rpc({"t": "get", "key": f"{key:064x}"},
                             (c.host, c.port))
            if resp is None:
                return None            # no answer -> suspect path
            if resp.get("t") == "value":
                try:
                    rec = SignedRecord.from_wire(resp["record"])
                except (KeyError, ValueError, TypeError):
                    return (None, [])
                return (rec if rec.verify(expect_key=key) else None), []
            if resp.get("t") == "nodes":
                return None, [Contact.from_wire(d)
                              for d in resp.get("nodes", [])]
            return (None, [])

        deadline = (time.monotonic() + budget_s
                    if budget_s is not None else None)
        best, _ = self._iterate(key, q, deadline=deadline)
        if local is not None and (best is None or local.seq > best.seq):
            best = local
        return best

    def put_self_record(self, username: str, addrs: list[str]) -> int:
        return self.put_record(SignedRecord.create(self.ident, username, addrs))


def parse_seeds(s: str) -> list[tuple[str, int]]:
    """Parse ``DHT_BOOTSTRAP``: comma-separated host:port pairs. Malformed
    entries are skipped with a warning — one typo must not kill the whole
    join (the node treats every DHT failure as non-fatal)."""
    seeds = []
    for part in filter(None, (p.strip() for p in s.split(","))):
        host, _, port = part.rpartition(":")
        try:
            seeds.append((host or "127.0.0.1", int(port)))
        except ValueError:
            log.warning("ignoring malformed DHT_BOOTSTRAP entry %r", part)
    return seeds
