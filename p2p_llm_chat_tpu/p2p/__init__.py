"""Encrypted P2P transport substrate.

This is the from-scratch equivalent of the reference's L0 layer (go-libp2p:
noise-encrypted, authenticated streams with stable peer identities —
SURVEY.md §1 L0). Design, not a port:

- **Identity** (:mod:`identity`): Ed25519 static keys; the peer ID is the
  base58 of a 2-byte type tag + raw public key, so any party can recover
  the public key from a peer ID and authenticate the remote end of a
  handshake against a directory record alone.
- **Transport** (:mod:`transport`): Noise-XX-style handshake (X25519
  ephemeral ECDH -> HKDF -> per-direction ChaCha20-Poly1305 keys, both
  sides sign the transcript with their static Ed25519 key), then
  length-prefixed encrypted frames over TCP. One stream per message with
  whole-stream framing, matching the reference's open->write->close
  pattern (go/cmd/node/main.go:245-261).
- **Multiaddrs** (:mod:`addr`): textual addresses keep the reference's
  ``/ip4/<ip>/tcp/<port>/p2p/<peer-id>`` shape (go/cmd/node/main.go:176-181)
  so directory records stay wire-compatible, plus ``/p2p-circuit/`` for
  relayed paths.
"""

from .identity import Identity, peer_id_to_public_key
from .addr import Multiaddr
from .transport import P2PHost, SecureStream

__all__ = ["Identity", "peer_id_to_public_key", "Multiaddr", "P2PHost", "SecureStream"]
