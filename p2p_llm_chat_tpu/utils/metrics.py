"""In-tree metrics: counters, gauges, and latency histograms.

The reference has no observability beyond stdout logs (SURVEY.md §5); the
serving benchmarks (tokens/sec/chip, p50 TTFT — BASELINE.md) *are* metrics,
so they are first-class here. Prometheus-style text rendering on /metrics;
percentiles computed from a bounded reservoir.
"""

from __future__ import annotations

import threading
from typing import Optional


class Counter:
    def __init__(self, name: str, help_: str = "") -> None:
        self.name = name
        self.help = help_
        self._v = 0.0
        self._mu = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._mu:
            self._v += amount

    @property
    def value(self) -> float:
        with self._mu:
            return self._v

    def render(self) -> str:
        return f"# TYPE {self.name} counter\n{self.name} {self.value}\n"


class Gauge:
    def __init__(self, name: str, help_: str = "") -> None:
        self.name = name
        self.help = help_
        self._v = 0.0
        self._mu = threading.Lock()

    def set(self, v: float) -> None:
        with self._mu:
            self._v = v

    def add(self, d: float) -> None:
        with self._mu:
            self._v += d

    @property
    def value(self) -> float:
        with self._mu:
            return self._v

    def render(self) -> str:
        return f"# TYPE {self.name} gauge\n{self.name} {self.value}\n"


class Histogram:
    """Bounded-reservoir histogram; keeps the most recent ``cap`` samples for
    percentile queries (enough for p50/p95/p99 dashboards and the bench)."""

    def __init__(self, name: str, help_: str = "", cap: int = 4096) -> None:
        self.name = name
        self.help = help_
        self._cap = cap
        self._samples: list[float] = []
        self._idx = 0
        self._count = 0
        self._sum = 0.0
        self._mu = threading.Lock()

    def observe(self, v: float) -> None:
        with self._mu:
            self._count += 1
            self._sum += v
            if len(self._samples) < self._cap:
                self._samples.append(v)
            else:
                self._samples[self._idx] = v
                self._idx = (self._idx + 1) % self._cap

    def percentile(self, p: float) -> Optional[float]:
        with self._mu:
            if not self._samples:
                return None
            xs = sorted(self._samples)
        k = min(len(xs) - 1, max(0, int(round(p / 100.0 * (len(xs) - 1)))))
        return xs[k]

    @property
    def count(self) -> int:
        with self._mu:
            return self._count

    @property
    def sum(self) -> float:
        with self._mu:
            return self._sum

    def render(self) -> str:
        lines = [f"# TYPE {self.name} summary"]
        for q, label in ((50, "0.5"), (95, "0.95"), (99, "0.99")):
            v = self.percentile(q)
            if v is not None:
                lines.append(f'{self.name}{{quantile="{label}"}} {v}')
        lines.append(f"{self.name}_sum {self.sum}")
        lines.append(f"{self.name}_count {self.count}")
        return "\n".join(lines) + "\n"


class Registry:
    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._metrics: dict[str, object] = {}

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get(name, lambda: Counter(name, help_), Counter)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get(name, lambda: Gauge(name, help_), Gauge)

    def histogram(self, name: str, help_: str = "") -> Histogram:
        return self._get(name, lambda: Histogram(name, help_), Histogram)

    def _get(self, name, factory, cls):
        with self._mu:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            if not isinstance(m, cls):
                raise TypeError(f"metric {name} already registered as {type(m).__name__}")
            return m

    def render(self) -> str:
        with self._mu:
            metrics = list(self._metrics.values())
        return "".join(m.render() for m in metrics)  # type: ignore[attr-defined]
