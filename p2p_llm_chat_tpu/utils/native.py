"""Loader for the in-tree native (C++) runtime components.

The reference's native-performance pieces live out-of-tree in Ollama's
C++ runtime; ours live in ``native/`` as small C-ABI shared objects
consumed via ctypes (no pybind11 in this image). Loading is lazy and
fail-soft: if the library is missing we try one quiet ``make``; if the
toolchain is unavailable the caller falls back to its pure-Python path,
so the framework never *requires* the native build.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

from .log import get_logger

log = get_logger("native")

_NATIVE_DIR = os.environ.get("NATIVE_LIB_DIR") or os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native")

_lock = threading.Lock()
_cache: dict[str, object] = {}


def load(name: str) -> object | None:
    """dlopen ``native/lib<name>.so``, building it on first miss.

    Returns the ctypes.CDLL or None (caller falls back to Python).
    Results (including failures) are cached per process.
    """
    with _lock:
        if name in _cache:
            return _cache[name]
        path = os.path.join(_NATIVE_DIR, f"lib{name}.so")
        if not os.path.exists(path):
            try:
                subprocess.run(["make", "-C", _NATIVE_DIR, f"lib{name}.so"],
                               capture_output=True, timeout=120, check=True)
            except Exception as e:   # noqa: BLE001 — missing toolchain etc.
                log.info("native %s unavailable (build failed: %s); "
                         "using pure-Python path", name, e)
                _cache[name] = None
                return None
        try:
            lib = ctypes.CDLL(path)
        except OSError as e:
            log.info("native %s unavailable (%s); using pure-Python path",
                     name, e)
            lib = None
        _cache[name] = lib
        return lib
