"""Persistent XLA compilation cache for the serving/bench planes.

An 8B serve boot compiles ~10 programs (admit chunk-sizes x buckets,
decode windows, prefix splices); through the tunnel's remote compiler
that measured ~18 minutes of warmup on a cold process. The JAX
persistent cache keys compiled executables by HLO fingerprint on local
disk, so every boot after the first reuses them — warmup drops to cache
reads. Tests set their own cache (tests/conftest.py); this helper covers
the production entrypoints (serve engine, bench, launcher children).

``JAX_CACHE_DIR`` overrides the location; ``0``/``off`` disables.
"""

from __future__ import annotations

import os

from .env import env_or
from .log import get_logger

log = get_logger("jax_cache")

_DEFAULT = "~/.cache/p2pchat-jax"
_enabled = False


def enable_persistent_cache() -> None:
    """Idempotent; call before the first jit compilation."""
    global _enabled
    if _enabled:
        return
    raw = env_or("JAX_CACHE_DIR", _DEFAULT)
    if raw.lower() in ("0", "off", ""):
        return
    path = os.path.abspath(os.path.expanduser(raw))
    try:
        os.makedirs(path, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        _enabled = True
        log.info("persistent compile cache at %s", path)
    except Exception as e:  # noqa: BLE001 — cache is an optimization only
        log.warning("compile cache disabled (%s)", e)
