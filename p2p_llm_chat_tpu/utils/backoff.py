"""Jittered exponential backoff + bounded retry helper.

The control plane's clients (directory register/lookup, DHT RPCs, the
node's re-register loop) all retry against services that fail together —
a restarted directory sees every node's retry at once. Bare fixed-delay
retries synchronize into thundering herds; this module is the one shared
implementation of the standard antidote (exponential growth, full
decorrelation jitter, a cap), so the retry policy cannot drift per
call site.

Every retry performed through :func:`with_retries` (or counted manually
via :func:`note_retry`) increments a process-global counter exported on
the serve front's ``/metrics`` as ``retry_attempts_total`` — an overload
or outage shows up as a retry-rate spike, not silence.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional, TypeVar

T = TypeVar("T")

_mu = threading.Lock()
_retries_total = 0                 # guarded-by: _mu


def note_retry(n: int = 1) -> None:
    global _retries_total
    with _mu:
        _retries_total += n


def retries_total() -> int:
    with _mu:
        return _retries_total


class Backoff:
    """Exponential delay sequence with full jitter.

    ``next()`` returns the next delay: uniformly sampled from
    [base * (1 - jitter), base] where base doubles (``factor``) per call
    up to ``max_s`` — the "full jitter" end of the AWS-architecture
    spectrum, which decorrelates a fleet retrying in lockstep.
    ``reset()`` returns to the initial delay after a success."""

    def __init__(self, base_s: float, max_s: float,
                 factor: float = 2.0, jitter: float = 0.5) -> None:
        if base_s <= 0 or max_s < base_s:
            raise ValueError(f"need 0 < base_s <= max_s, got "
                             f"{base_s=} {max_s=}")
        if not 0.0 <= jitter <= 1.0:
            raise ValueError(f"jitter must be in [0,1], got {jitter}")
        self.base_s = base_s
        self.max_s = max_s
        self.factor = factor
        self.jitter = jitter
        self._cur = base_s

    def next(self) -> float:
        cur = self._cur
        self._cur = min(self._cur * self.factor, self.max_s)
        lo = cur * (1.0 - self.jitter)
        return random.uniform(lo, cur) if self.jitter else cur

    def peek(self) -> float:
        """The undithered current delay (what next() grows from)."""
        return self._cur

    def reset(self) -> None:
        self._cur = self.base_s


def with_retries(fn: Callable[[], T], *, attempts: int = 3,
                 base_s: float = 0.2, max_s: float = 2.0,
                 jitter: float = 0.5,
                 retry_on: tuple = (ConnectionError,),
                 budget_s: Optional[float] = None) -> T:
    """Call ``fn`` with up to ``attempts`` tries, jittered-exponential
    sleeps in between. Only ``retry_on`` exceptions retry (a 404 is an
    answer, not an outage); the last failure re-raises. ``budget_s``
    bounds total wall time: no retry starts once elapsed + the next
    delay would exceed it (the /send handler runs lookups inline — a
    dead black-hole directory must not hold the UI's request for
    attempts x timeout)."""
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    bo = Backoff(base_s, max_s, jitter=jitter)
    t0 = time.monotonic()
    for i in range(attempts):
        try:
            return fn()
        except retry_on:
            if i + 1 >= attempts:
                raise
            delay = bo.next()
            if (budget_s is not None
                    and time.monotonic() - t0 + delay > budget_s):
                raise
            note_retry()
            time.sleep(delay)
    raise AssertionError("unreachable")
