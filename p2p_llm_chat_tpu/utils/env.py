"""Env-var-first configuration helpers.

The reference configures every process purely through environment variables
read via tiny helpers (``envOr`` at go/cmd/node/main.go:286-291, ``getenv`` at
go/cmd/directory/main.go:100-109). We keep that contract — the same variable
names keep working — and layer typed accessors on top.
"""

from __future__ import annotations

import os


def env_or(key: str, default: str) -> str:
    """Return ``os.environ[key]`` if set and non-empty, else ``default``.

    Mirrors ``envOr`` (go/cmd/node/main.go:286-291): empty string counts as
    unset.
    """
    v = os.environ.get(key, "")
    return v if v != "" else default


def env_int(key: str, default: int) -> int:
    v = os.environ.get(key, "")
    if v == "":
        return default
    return int(v)


def env_float(key: str, default: float) -> float:
    v = os.environ.get(key, "")
    if v == "":
        return default
    return float(v)


def env_opt(key: str, default: str) -> str:
    """Return ``os.environ[key]`` if SET — even when empty — else ``default``.

    The one sanctioned exception to ``env_or``'s empty-is-unset contract,
    for optional-feature flags whose documented OFF spelling is the empty
    string (``BENCH_QUANT=`` = plain bf16, ``BENCH_KV_QUANT=`` = bf16 KV
    pool). graftcheck's env-hygiene analyzer recognizes it alongside the
    typed helpers.
    """
    return os.environ.get(key, default)


def env_bool(key: str, default: bool = False) -> bool:
    v = os.environ.get(key, "").strip().lower()
    if v == "":
        return default
    return v in ("1", "true", "yes", "on")
