"""In-tree failpoint registry: stack-wide fault injection.

Every layer of the serve + P2P planes threads named sites through this
module (``failpoint("site.name")``) — a no-op by default, armable from
the environment or at runtime to inject the faults the chaos suites
drive (tests/test_failpoints.py). The practice follows FreeBSD's
``fail(9)`` / TiKV's ``fail-rs``: partial failure is a first-class,
*tested* behavior, not an emergent one — every site has a test that
arms it and asserts the degradation contract (no deadlock, well-formed
error or recovery, oracle-exact completed greedy output).

Arming grammar (``FAIL_POINTS`` env var or :func:`arm`)::

    site=action[:arg][*count][@prob]

comma- or semicolon-separated entries. Actions:

- ``raise[:MSG]``   raise :class:`FailpointError` at the site (the
  caller's existing error path must degrade gracefully);
- ``delay:MS``      sleep MS milliseconds, then continue (latency
  injection — slow disks, slow networks, GC pauses);
- ``drop``          the caller discards the current item (a lost
  datagram, a dropped control frame) — sites that support it check the
  returned action's ``kind``;
- ``error[:MSG]``   the caller returns a well-formed error instead of
  proceeding (an HTTP 500 record, a refused RPC) — also checked via
  the returned action.

Modifiers: ``*N`` fires only the first N hits then self-disarms
(deterministic one-shot faults for recovery tests); ``@P`` fires with
probability P in [0, 1] (background fault rates for chaos runs).

Hit counters are per-site, monotonic, and exported on the serve front's
``/metrics`` as ``failpoint_hits_total{site="..."}`` (serve/api.py) —
a chaos run can assert its faults actually fired, and an operator can
see that a production binary has NO armed sites (no series present).

The disarmed fast path is one dict lookup — cheap enough for the decode
loop's per-tick sites (the all-disarmed bench bar in ISSUE 5 holds the
regression under 1%).

Site catalog (``KNOWN_SITES``; docs/robustness.md documents each site's
degradation contract):

===========================  ===============================================
``serve.api.parse``          request parse/validate in the HTTP front
``serve.api.stream``         per-delta NDJSON stream yield
``serve.scheduler.admit``    admission prefill dispatch
``serve.scheduler.dispatch`` decode-tick dispatch
``serve.scheduler.promote``  off-thread prefix-promotion build
``serve.engine.readback``    decode-tick token readback (device -> host)
``serve.kv_tier.export``     session-payload serialize for a peer replica
``serve.kv_tier.import``     session-payload install from a peer replica
``serve.router.migrate``     one session's drain/retire migration step
``serve.disagg.handoff``     one prefill→decode handoff (router side)

``p2p.directory.register``   directory client register RPC
``p2p.directory.lookup``     directory client lookup RPC
``p2p.directory.evict``      directory TTL eviction of one stale record
``p2p.dht.rpc``              one DHT UDP RPC attempt (drop = lost dgram)
``p2p.relay.control``        relay-service control-frame handling
``p2p.transport.handshake``  secure-channel dial handshake
``p2p.node.deliver``         one chat-message delivery attempt (per addr)
``p2p.node.resolve``         redelivery-round recipient re-resolution
===========================  ===============================================
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Optional

from .env import env_or
from .log import get_logger

log = get_logger("failpoints")

KNOWN_SITES = (
    "serve.api.parse",
    "serve.api.stream",
    "serve.scheduler.admit",
    "serve.scheduler.dispatch",
    "serve.scheduler.promote",
    "serve.engine.readback",
    "serve.kv_tier.export",
    "serve.kv_tier.import",
    "serve.router.migrate",
    "serve.disagg.handoff",
    "p2p.directory.register",
    "p2p.directory.lookup",
    "p2p.directory.evict",
    "p2p.dht.rpc",
    "p2p.relay.control",
    "p2p.transport.handshake",
    "p2p.node.deliver",
    "p2p.node.resolve",
)

_ACTIONS = ("raise", "delay", "drop", "error")


class FailpointError(RuntimeError):
    """Raised at a site armed with the ``raise`` action. Subclasses
    RuntimeError so every existing degrade-don't-crash handler (the
    scheduler's recovery envelope, the router's 500 mapping, the node's
    lookup-ladder fallbacks) treats it like any unexpected fault."""


@dataclass
class Action:
    """One armed site's behavior. Returned from :func:`failpoint` for
    the caller-interpreted kinds (``drop``/``error``); ``raise`` and
    ``delay`` are handled inside the registry."""

    kind: str
    msg: str = ""
    delay_s: float = 0.0
    remaining: int = -1            # *N modifier; -1 = unlimited
    prob: float = 1.0              # @P modifier


_mu = threading.Lock()
_armed: dict[str, Action] = {}     # guarded-by: _mu (reads are lock-free:
#                                    per-site get of an immutable-enough
#                                    entry; mutation always under _mu)
_hits: dict[str, int] = {}         # guarded-by: _mu
_env_loaded = False


def parse_spec(spec: str) -> Action:
    """``action[:arg][*count][@prob]`` -> :class:`Action` (ValueError on
    anything malformed — a typo'd chaos config must fail loudly, not
    silently not inject)."""
    prob = 1.0
    remaining = -1
    body = spec.strip()
    if "@" in body:
        body, _, p = body.rpartition("@")
        prob = float(p)
        if not 0.0 <= prob <= 1.0:
            raise ValueError(f"failpoint prob must be in [0,1]: {spec!r}")
    if "*" in body:
        body, _, n = body.rpartition("*")
        remaining = int(n)
        if remaining < 1:
            raise ValueError(f"failpoint count must be >= 1: {spec!r}")
    kind, _, arg = body.partition(":")
    kind = kind.strip()
    if kind not in _ACTIONS:
        raise ValueError(
            f"unknown failpoint action {kind!r} (expected one of "
            f"{'/'.join(_ACTIONS)}): {spec!r}")
    delay_s = 0.0
    msg = ""
    if kind == "delay":
        if not arg:
            raise ValueError(f"delay needs milliseconds: {spec!r}")
        delay_s = float(arg) / 1e3
    else:
        msg = arg
    return Action(kind=kind, msg=msg, delay_s=delay_s,
                  remaining=remaining, prob=prob)


def arm(site: str, spec: str) -> None:
    """Arm ``site`` with ``spec`` (see module docstring grammar). A site
    outside :data:`KNOWN_SITES` arms with a WARNING, not an error —
    tests arm scratch sites freely, but a typo'd production site would
    otherwise silently inject nothing."""
    act = parse_spec(spec)
    if site not in KNOWN_SITES:
        log.warning("failpoint site %r is not in the known-site catalog "
                    "(typo? see docs/robustness.md); arming anyway", site)
    with _mu:
        _armed[site] = act
    log.info("failpoint armed: %s=%s", site, spec)


def disarm(site: str) -> None:
    with _mu:
        _armed.pop(site, None)


def disarm_all() -> None:
    with _mu:
        _armed.clear()


def reset_hits() -> None:
    with _mu:
        _hits.clear()


def hits(site: str) -> int:
    with _mu:
        return _hits.get(site, 0)


def snapshot() -> dict[str, int]:
    """Per-site hit counters (sites that ever fired), for /metrics."""
    with _mu:
        return dict(_hits)


def armed_sites() -> tuple[str, ...]:
    with _mu:
        return tuple(sorted(_armed))


def load_env(force: bool = False) -> None:
    """Parse ``FAIL_POINTS`` once (lazily on the first failpoint() of
    the process, eagerly from every service constructor — OllamaServer,
    ChatNode, DirectoryService, RelayService — so a malformed config
    fails AT BOOT, visibly, not at some arbitrary deep call site mid-
    serving). All-or-nothing: every entry parses before any arms, so a
    typo in entry 3 can never leave entries 1-2 partially armed.
    ``force`` re-reads — tests and long-lived operators re-arming at
    runtime use :func:`arm` instead."""
    global _env_loaded
    if _env_loaded and not force:
        return
    _env_loaded = True
    raw = env_or("FAIL_POINTS", "")
    if not raw:
        return
    parsed: list[tuple[str, str]] = []
    for entry in raw.replace(";", ",").split(","):
        entry = entry.strip()
        if not entry:
            continue
        site, sep, spec = entry.partition("=")
        if not sep:
            raise ValueError(
                f"FAIL_POINTS entry {entry!r} is not site=action")
        parse_spec(spec)                   # validate BEFORE arming any
        parsed.append((site.strip(), spec))
    for site, spec in parsed:
        arm(site, spec)


def failpoint(site: str) -> Optional[Action]:
    """Evaluate the named site. No-op (None) unless armed. ``raise``
    raises :class:`FailpointError`; ``delay`` sleeps then returns the
    action; ``drop``/``error`` return the action for the caller to
    interpret. Every fire increments the site's hit counter."""
    if not _env_loaded:
        load_env()
    act = _armed.get(site)
    if act is None:
        return None
    with _mu:
        # Re-check under the lock: a *N arm racing two threads must fire
        # exactly N times total.
        act = _armed.get(site)
        if act is None:
            return None
        if act.prob < 1.0:
            import random
            if random.random() >= act.prob:
                return None
        if act.remaining == 0:
            _armed.pop(site, None)
            return None
        if act.remaining > 0:
            act.remaining -= 1
            if act.remaining == 0:
                _armed.pop(site, None)
        _hits[site] = _hits.get(site, 0) + 1
    if act.kind == "raise":
        raise FailpointError(
            act.msg or f"failpoint {site!r} armed (injected fault)")
    if act.kind == "delay":
        time.sleep(act.delay_s)
    return act
