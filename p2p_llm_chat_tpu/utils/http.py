"""Tiny threaded HTTP framework + JSON client used by every service.

The reference builds its HTTP surfaces on gin (go/cmd/node/main.go:214,
go/cmd/directory/main.go:59). This module is our in-tree equivalent: a
route table on top of stdlib ``ThreadingHTTPServer`` (no framework
dependency, trivially embeddable in tests) and a matching ``http_json``
client helper with the same timeout discipline the reference uses
(5 s directory client timeout, go/cmd/node/main.go:175).
"""

from __future__ import annotations

import json
import socket
import threading
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Iterator, Optional

from .log import get_logger

log = get_logger("http")


@dataclass
class Request:
    method: str
    path: str
    query: dict[str, str]
    headers: dict[str, str]
    body: bytes

    def json(self) -> Any:
        if not self.body:
            return None
        return json.loads(self.body.decode("utf-8"))


@dataclass
class Response:
    status: int = 200
    body: Any = None           # JSON-serialisable, or bytes/str for raw
    content_type: str = "application/json"
    headers: dict[str, str] = field(default_factory=dict)
    # When set, the response is sent with Transfer-Encoding: chunked, one
    # chunk per yielded bytes object (used for Ollama-style NDJSON streams).
    stream: Optional[Iterator[bytes]] = None

    def encode(self) -> bytes:
        if self.body is None:
            return b""
        if isinstance(self.body, bytes):
            return self.body
        if isinstance(self.body, str):
            return self.body.encode("utf-8")
        return json.dumps(self.body).encode("utf-8")


Handler = Callable[[Request], Response]


class Router:
    """Maps (METHOD, exact-path) -> handler. Query strings are parsed off."""

    def __init__(self) -> None:
        self._routes: dict[tuple[str, str], Handler] = {}
        self._fallback: Optional[Handler] = None

    def route(self, method: str, path: str) -> Callable[[Handler], Handler]:
        def deco(fn: Handler) -> Handler:
            self._routes[(method.upper(), path)] = fn
            return fn
        return deco

    def add(self, method: str, path: str, fn: Handler) -> None:
        self._routes[(method.upper(), path)] = fn

    def set_fallback(self, fn: Handler) -> None:
        """Handler consulted when no exact route matches (e.g. static files)."""
        self._fallback = fn

    def dispatch(self, req: Request) -> Response:
        fn = self._routes.get((req.method, req.path))
        if fn is None and self._fallback is not None:
            fn = self._fallback
        if fn is None:
            return Response(404, {"error": "not found"})
        try:
            return fn(req)
        except Exception as e:  # noqa: BLE001 — surface as 500, keep serving
            log.exception("handler error on %s %s", req.method, req.path)
            return Response(500, {"error": str(e)})


class HttpServer:
    """Threaded HTTP server wrapping a Router; one thread per request."""

    def __init__(self, router: Router, addr: str = "127.0.0.1:0") -> None:
        if addr.startswith(":"):
            addr = "127.0.0.1" + addr
        host, _, port = addr.rpartition(":")
        host = host or "127.0.0.1"
        router_ref = router

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _handle(self) -> None:
                parsed = urllib.parse.urlsplit(self.path)
                query = {k: v[0] for k, v in urllib.parse.parse_qs(parsed.query).items()}
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                req = Request(
                    method=self.command,
                    path=parsed.path,
                    query=query,
                    headers={k.lower(): v for k, v in self.headers.items()},
                    body=body,
                )
                resp = router_ref.dispatch(req)
                if resp.stream is not None:
                    self.send_response(resp.status)
                    self.send_header("Content-Type", resp.content_type)
                    self.send_header("Transfer-Encoding", "chunked")
                    for k, v in resp.headers.items():
                        self.send_header(k, v)
                    self.end_headers()
                    # Terminate the chunked stream ONLY on clean completion:
                    # a mid-stream failure must look truncated to the client
                    # (dropped connection), not like a well-formed response.
                    # A client hanging up mid-stream (browser tab closed,
                    # loadgen driver done with the deltas it needed) is
                    # normal operation, not a server error — swallow the
                    # reset instead of letting socketserver print a
                    # traceback per disconnect (at 64-peer load that is
                    # a log storm).
                    try:
                        try:
                            for chunk in resp.stream:
                                if not chunk:
                                    continue
                                self.wfile.write(
                                    f"{len(chunk):x}\r\n".encode())
                                self.wfile.write(chunk)
                                self.wfile.write(b"\r\n")
                                self.wfile.flush()
                            self.wfile.write(b"0\r\n\r\n")
                        except (ConnectionResetError, BrokenPipeError):
                            log.debug(
                                "client disconnected mid-stream on %s %s",
                                self.command, parsed.path)
                            self.close_connection = True
                    finally:
                        # Run the generator's finally blocks NOW
                        # (inflight gauges, stats observers, upstream
                        # connections) rather than at some later GC — on
                        # EVERY exit path, not just the two reset types:
                        # a socket timeout or any other write error that
                        # propagates out of the chunk loop must settle
                        # the gauges too (a no-op when the generator ran
                        # to exhaustion).
                        try:
                            resp.stream.close()
                        except Exception:  # noqa: BLE001 — teardown only
                            pass
                    return
                payload = resp.encode()
                self.send_response(resp.status)
                self.send_header("Content-Type", resp.content_type)
                self.send_header("Content-Length", str(len(payload)))
                for k, v in resp.headers.items():
                    self.send_header(k, v)
                self.end_headers()
                if payload and self.command != "HEAD":
                    self.wfile.write(payload)

            do_GET = do_POST = do_PUT = do_DELETE = do_HEAD = _handle

            def log_message(self, fmt: str, *args: Any) -> None:
                log.debug("%s %s", self.address_string(), fmt % args)

        class _Server(ThreadingHTTPServer):
            daemon_threads = True
            # The socketserver default backlog (5) RESETS most of a
            # 32-peer simultaneous suggestion burst before accept() ever
            # sees it — observed as "(LLM unavailable: Connection reset
            # by peer)" at every UI when 8B decode holds connections open
            # for seconds. One co-pilot burst = one connection per peer,
            # so size the backlog to hundreds of peers.
            request_queue_size = 256

        self._httpd = _Server((host, int(port)), _Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def addr(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"{host}:{port}"

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        """Dialable base URL (wildcard binds rewritten to loopback)."""
        host, port = self._httpd.server_address[:2]
        if host in ("0.0.0.0", "::"):
            host = "127.0.0.1"
        return f"http://{host}:{port}"

    def start(self) -> "HttpServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._httpd.serve_forever()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)


class HttpError(Exception):
    def __init__(self, status: int, body: str) -> None:
        super().__init__(f"HTTP {status}: {body[:200]}")
        self.status = status
        self.body = body


def http_json(
    method: str,
    url: str,
    body: Any = None,
    timeout: float = 5.0,
    raise_for_status: bool = True,
    headers: Optional[dict] = None,
) -> tuple[int, Any]:
    """Minimal JSON-over-HTTP client. Returns (status, parsed-json-or-None).
    ``headers`` merge under the computed Content-Type — the hook proxy
    hops use to forward X-Graft-Trace / X-Session-Id."""
    data = None
    hdrs = dict(headers or {})
    if body is not None:
        data = json.dumps(body).encode("utf-8")
        hdrs["Content-Type"] = "application/json"
    req = urllib.request.Request(url, data=data, headers=hdrs, method=method.upper())
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            raw = resp.read()
            status = resp.status
    except urllib.error.HTTPError as e:
        raw = e.read()
        status = e.code
        if raise_for_status:
            raise HttpError(status, raw.decode("utf-8", "replace")) from None
    except (urllib.error.URLError, socket.timeout, ConnectionError, OSError) as e:
        raise ConnectionError(f"{method} {url}: {e}") from None
    parsed = json.loads(raw.decode("utf-8")) if raw else None
    return status, parsed
