"""Logging setup shared by all services.

The reference logs with stdlib ``log`` plus emoji markers (go/cmd/node/main.go:171,
186, 280). We use Python logging with a compact single-line format; services call
``get_logger(name)`` and log at info for lifecycle events, debug for per-request
detail.
"""

from __future__ import annotations

import logging
import os
import sys

_CONFIGURED = False


def _configure() -> None:
    global _CONFIGURED
    if _CONFIGURED:
        return
    level = os.environ.get("LOG_LEVEL", "INFO").upper()
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        logging.Formatter("%(asctime)s %(levelname).1s %(name)s: %(message)s", "%H:%M:%S")
    )
    root = logging.getLogger("p2pchat")
    root.setLevel(level)
    root.addHandler(handler)
    root.propagate = False
    _CONFIGURED = True


def get_logger(name: str) -> logging.Logger:
    _configure()
    return logging.getLogger(f"p2pchat.{name}")
