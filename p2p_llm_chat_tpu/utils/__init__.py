"""Shared utilities: env config, logging, base58, HTTP micro-framework, metrics."""
