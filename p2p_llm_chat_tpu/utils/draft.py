"""Prompt-lookup drafting for speculative decoding.

Reply suggestions quote and rephrase their context heavily (the co-pilot
prompt embeds the peer's message verbatim — web/streamlit_app.py:93), so a
draft model is unnecessary: proposing the continuation that followed the
most recent earlier occurrence of the current trailing n-gram gets long
accepted runs for free. The verify pass (models/llama.verify_step +
sampling.spec_verify_batched) scores the whole draft in one forward.

The index is incremental: O(1) per generated token, last occurrence wins
(recency beats frequency for chat text).
"""

from __future__ import annotations


class NGramDrafter:
    """Per-request n-gram index over prompt + generated ids."""

    def __init__(self, ids: list[int], k: int, n: int = 2) -> None:
        self.k = k
        self.n = n
        self.ids = list(ids)
        # ngram tuple -> position just after its latest occurrence,
        # excluding the trailing ngram itself (its continuation doesn't
        # exist yet — it's what we're trying to predict).
        self._index: dict[tuple, int] = {}
        for i in range(len(self.ids) - n):
            self._index[tuple(self.ids[i: i + n])] = i + n

    def append(self, tok: int) -> None:
        if len(self.ids) >= self.n:
            self._index[tuple(self.ids[-self.n:])] = len(self.ids)
        self.ids.append(tok)

    def draft(self) -> list[int]:
        """Up to k proposed continuation tokens ([] = no match)."""
        if len(self.ids) < self.n:
            return []
        pos = self._index.get(tuple(self.ids[-self.n:]))
        if pos is None:
            return []
        return self.ids[pos: pos + self.k]
