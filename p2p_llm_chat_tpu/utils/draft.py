"""Draft sources for speculative decoding.

Two draft sources live behind one scheduler-facing protocol
(:class:`DraftSource`):

- **Prompt-lookup n-grams** (:class:`NGramDrafter` per row, batched as
  :class:`NGramSource`). Reply suggestions quote and rephrase their
  context heavily (the co-pilot prompt embeds the peer's message
  verbatim — web/streamlit_app.py:93), so proposing the continuation
  that followed the most recent earlier occurrence of the current
  trailing n-gram gets long accepted runs for FREE — no second model,
  ~0 host cost. But it measures ~0 acceptances on free-form output
  (models/synth.py docstring: 251/256 unique tokens), so the quote-heavy
  statistic was the only workload where speculation won.
- **A resident draft model** (serve/draft_model.ModelDrafter): a small
  model on the same chip proposes K greedy tokens autoregressively —
  the classic draft-target scheme (Leviathan et al. 2023; Chen et al.
  2023) that generalises the win to every workload, at the cost of a
  drafter dispatch per spec tick. It lives in serve/ (it owns device
  state and reuses the model stack); this module holds only the
  host-side protocol both sources implement.

The scheduler consults sources in priority order per row — n-gram
first (free when it hits), model drafts filling in on n-gram misses —
and throttles each source independently on its own acceptance EMA
(serve/scheduler.py), so a cold n-gram index cannot throttle model
drafting. Either way the verify pass (models/llama.verify_step +
sampling.spec_verify_batched) scores the whole draft in one forward;
both sources propose point-mass (deterministic) drafts, which is what
keeps the acceptance math distribution-exact.

The n-gram index is incremental: O(1) per generated token, last
occurrence wins (recency beats frequency for chat text).
"""

from __future__ import annotations


class DraftSource:
    """Scheduler-facing draft-source protocol (batch-level: one instance
    serves every batch row — the model drafter must dispatch ONE batched
    device program per tick, not one per row, so the per-row NGramDrafter
    shape cannot be the shared interface).

    Lifecycle hooks mirror the scheduler's slot lifecycle; every method
    is called from the scheduler thread only. ``draft_batch`` proposes
    up to k tokens per requested row; ``observe`` reports the verify
    outcome so stateful sources (the model drafter's KV) can roll back
    to the last accepted position. All proposals must be DETERMINISTIC
    functions of the row context (point-mass draft distribution) — the
    exact-acceptance math in models/sampling.spec_verify_batched relies
    on it."""

    name: str = "?"

    def admit(self, row: int, ctx: list[int]) -> None:
        """Row entered the batch with ``ctx`` (prompt ids) as context."""

    def append(self, row: int, tok: int) -> None:
        """One token was accepted into the row's context (plain ticks,
        accepted drafts, corrections — every emitted token)."""

    def release(self, row: int) -> None:
        """Row left the batch."""

    def draft_batch(self, rows: list[int],
                    ctxs: dict[int, tuple[list[int], list[int]]]
                    ) -> dict[int, list[int]]:
        """Proposals for ``rows``: row -> up to k draft tokens ([] /
        missing = no proposal). ``ctxs[row]`` is the row's context as
        the UNCONCATENATED ``(prompt_ids, generated_ids)`` pair — the
        scheduler passes its live list references, so a spec tick costs
        no per-row context copies; sources slice only what they need
        (the model drafter: the suffix past its fed prefix)."""
        raise NotImplementedError

    def draft_tree_batch(self, rows: list[int],
                         ctxs: dict[int, tuple[list[int], list[int]]]
                         ) -> dict[int, tuple[list[int], list[int],
                                              list[float]]]:
        """Tree proposals: row -> (main_chain, second_choices, gaps).
        ``main_chain`` is exactly what :meth:`draft_batch` would
        propose; ``second_choices[j]``/``gaps[j]`` are the source's
        second-best token at main position j and its top-1/top-2 score
        gap (smaller = less certain = better branch site). The default
        degrades to a LINEAR chain — empty second/gap lists, so the
        scheduler budgets no siblings and the tree is a path
        (NGramSource proposes from a lookup table with no runner-up
        score; it rides tree ticks unchanged this way). Sources with
        real runner-up scores (serve/draft_model.ModelDrafter)
        override. ``observe`` still reports the MAIN-CHAIN accepted
        prefix only — a used sibling diverges from this source's fed
        state, so it must not be counted as fed context."""
        return {r: (d, [], [])
                for r, d in self.draft_batch(rows, ctxs).items()}

    def observe(self, row: int, accepted: int) -> None:
        """Verify outcome for a row this source drafted this tick."""

    def reset(self) -> None:
        """Scheduler device-state reset — drop everything."""


class NGramSource(DraftSource):
    """Prompt-lookup drafting behind the batch protocol: one incremental
    :class:`NGramDrafter` per live row."""

    name = "ngram"

    def __init__(self, k: int, n: int = 2) -> None:
        self.k = k
        self.n = n
        self._rows: dict[int, NGramDrafter] = {}

    def admit(self, row: int, ctx: list[int]) -> None:
        self._rows[row] = NGramDrafter(ctx, self.k, n=self.n)

    def append(self, row: int, tok: int) -> None:
        d = self._rows.get(row)
        if d is not None:
            d.append(tok)

    def release(self, row: int) -> None:
        self._rows.pop(row, None)

    def draft_batch(self, rows: list[int],
                    ctxs: dict[int, tuple[list[int], list[int]]]
                    ) -> dict[int, list[int]]:
        out: dict[int, list[int]] = {}
        for row in rows:
            d = self._rows.get(row)
            if d is None:
                # Late bind (e.g. source enabled after admission): build
                # the index from the full context once.
                prompt, ids = ctxs[row]
                d = self._rows[row] = NGramDrafter(list(prompt) + list(ids),
                                                   self.k, n=self.n)
            prop = d.draft()
            if prop:
                out[row] = prop
        return out

    def reset(self) -> None:
        self._rows.clear()


class NGramDrafter:
    """Per-request n-gram index over prompt + generated ids."""

    def __init__(self, ids: list[int], k: int, n: int = 2) -> None:
        self.k = k
        self.n = n
        self.ids = list(ids)
        # ngram tuple -> position just after its latest occurrence,
        # excluding the trailing ngram itself (its continuation doesn't
        # exist yet — it's what we're trying to predict).
        self._index: dict[tuple, int] = {}
        for i in range(len(self.ids) - n):
            self._index[tuple(self.ids[i: i + n])] = i + n

    def append(self, tok: int) -> None:
        if len(self.ids) >= self.n:
            self._index[tuple(self.ids[-self.n:])] = len(self.ids)
        self.ids.append(tok)

    def draft(self) -> list[int]:
        """Up to k proposed continuation tokens ([] = no match)."""
        if len(self.ids) < self.n:
            return []
        pos = self._index.get(tuple(self.ids[-self.n:]))
        if pos is None:
            return []
        return self.ids[pos: pos + self.k]
