"""Circuit relay daemon for NAT traversal.

Reference: go/cmd/relay/main.go — a standalone libp2p circuit-relay-v2 hop
with default resource limits that prints its multiaddrs and blocks forever.
Ours is the from-scratch equivalent for the in-tree transport (p2p/transport):

- NAT'd peers hold an authenticated *reservation* (Ed25519-signed, verified
  against the self-certifying peer id) over a persistent control connection.
- A dialer sends a HOP request naming the target peer; the relay signals the
  target over its control channel, the target dials back to ACCEPT, and the
  relay splices the two TCP connections byte-for-byte.
- The end-to-end secure handshake runs *through* the splice, so the relay
  never holds keys or sees plaintext — the property circuit-relay-v2
  provides in the reference.
- Resource limits in the spirit of relayv2 ``DefaultResources()``
  (go/cmd/relay/main.go:37): max reservations, max circuits, per-circuit
  idle timeout, pending-accept timeout.

Env: ``RELAY_ADDR`` (listen, default 127.0.0.1:4100), ``RELAY_MAX_RESERVATIONS``,
``RELAY_MAX_CIRCUITS``.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Optional

from .p2p import Identity, Multiaddr, peer_id_to_public_key
from .p2p.transport import (
    RELAY_ACCEPT,
    RELAY_HOP,
    RELAY_INCOMING,
    RELAY_PING,
    RELAY_PONG,
    RELAY_PUNCH,
    RELAY_PUNCH_ACK,
    RELAY_RESERVE,
    recv_json_frame,
    send_json_frame,
)
from .utils.env import env_int, env_or
from .utils.failpoints import (FailpointError, failpoint,
                               load_env as load_failpoints_env)
from .utils.log import get_logger
from .utils import native

log = get_logger("relay")

RESERVATION_STALE_S = 120.0     # control channel considered dead after this
CIRCUIT_IDLE_TIMEOUT_S = 300.0  # spliced circuit killed after idle
ACCEPT_TIMEOUT_S = 10.0         # target must dial back within this
RESERVE_TS_WINDOW_S = 60.0      # max |now - ts| on a signed RESERVE frame
SWEEP_INTERVAL_S = 30.0         # ping/evict cadence for reservations


@dataclass
class _Reservation:
    peer_id: str
    sock: socket.socket
    send_lock: threading.Lock = field(default_factory=threading.Lock)
    last_seen: float = field(default_factory=time.time)


@dataclass
class _PendingCircuit:
    dialer_sock: socket.socket
    event: threading.Event = field(default_factory=threading.Event)
    target_sock: Optional[socket.socket] = None


def _valid_udp_addr(v) -> Optional[tuple[str, int]]:
    """(host, port) from an untrusted wire value, or None. Punch addrs
    cross two trust boundaries (dialer -> relay -> target and back), so
    both hops validate instead of int()-ing whatever arrived."""
    try:
        if not isinstance(v, (list, tuple)) or len(v) != 2:
            return None
        host, port = str(v[0]), int(v[1])
        if not host or not 0 < port < 65536:
            return None
        return host, port
    except (TypeError, ValueError):
        return None


@dataclass
class _PendingPunch:
    event: threading.Event = field(default_factory=threading.Event)
    target_udp: Optional[list] = None


class RelayService:
    def __init__(self, addr: Optional[str] = None,
                 max_reservations: Optional[int] = None,
                 max_circuits: Optional[int] = None,
                 advertise_host: Optional[str] = None,
                 reserve_ts_window_s: float = RESERVE_TS_WINDOW_S,
                 stale_after_s: float = RESERVATION_STALE_S,
                 sweep_interval_s: float = SWEEP_INTERVAL_S) -> None:
        # Eager FAIL_POINTS parse: malformed chaos config fails at boot.
        load_failpoints_env()
        addr = addr if addr is not None else env_or("RELAY_ADDR", "127.0.0.1:4100")
        host, _, port = addr.rpartition(":")
        self._host = host or "127.0.0.1"
        self._port = int(port or 0)
        self._advertise_host = advertise_host or (
            self._host if self._host not in ("0.0.0.0", "::") else "127.0.0.1")
        self.identity = Identity.generate()
        self.max_reservations = (max_reservations if max_reservations is not None
                                 else env_int("RELAY_MAX_RESERVATIONS", 128))
        self.max_circuits = (max_circuits if max_circuits is not None
                             else env_int("RELAY_MAX_CIRCUITS", 1024))
        self.reserve_ts_window_s = reserve_ts_window_s
        self.stale_after_s = stale_after_s
        self.sweep_interval_s = sweep_interval_s
        self._reservations: dict[str, _Reservation] = {}
        self._pending: dict[str, _PendingCircuit] = {}
        self._pending_punch: dict[str, _PendingPunch] = {}
        self._active_circuits = 0
        self._n_spliced = 0          # circuits ever spliced (punch tests
        #                              assert direct paths keep this at 0)
        self._mu = threading.Lock()
        self._server: Optional[socket.socket] = None
        self._udp: Optional[socket.socket] = None
        self._closed = threading.Event()

    @property
    def peer_id(self) -> str:
        return self.identity.peer_id

    def addr(self) -> Multiaddr:
        return Multiaddr(self._advertise_host, self._port, peer_id=self.peer_id)

    def start(self) -> "RelayService":
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self._host, self._port))
        s.listen(128)
        self._port = s.getsockname()[1]
        self._server = s
        # STUN-lite UDP endpoint on the same port: answers "observe"
        # datagrams with the source address it saw, so NAT'd peers learn
        # their post-NAT UDP endpoint for hole punching (p2p/udp.py).
        # Best-effort: observe is an optional additive feature with a
        # graceful client fallback (observe_udp_addr tolerates silence),
        # so an unrelated process squatting the UDP port must not take
        # down circuit relaying.
        try:
            u = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            u.bind((self._host, self._port))
            self._udp = u
            threading.Thread(target=self._udp_observe_loop,
                             daemon=True).start()
        except OSError as e:
            log.warning("UDP observe endpoint unavailable on port %d (%s); "
                        "hole-punch endpoint discovery disabled", self._port, e)
            self._udp = None
        threading.Thread(target=self._accept_loop, daemon=True).start()
        threading.Thread(target=self._sweep_loop, daemon=True).start()
        # Print multiaddrs like the reference does (go/cmd/relay/main.go:40-45).
        log.info("relay %s listening; multiaddr: %s", self.peer_id[:12], self.addr())
        return self

    def stop(self) -> None:
        self._closed.set()
        if self._udp is not None:
            try:
                self._udp.close()
            except OSError:
                pass
        if self._server is not None:
            try:
                self._server.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._server.close()
            except OSError:
                pass
        with self._mu:
            for r in self._reservations.values():
                try:
                    r.sock.close()
                except OSError:
                    pass
            self._reservations.clear()

    def serve_forever(self) -> None:
        self.start()
        threading.Event().wait()    # block forever (go/cmd/relay/main.go:46)

    # -- connection handling -------------------------------------------------

    def _udp_observe_loop(self) -> None:
        assert self._udp is not None
        while not self._closed.is_set():
            try:
                data, addr = self._udp.recvfrom(2048)
            except OSError:
                return
            try:
                msg = json.loads(data.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                continue
            if msg.get("type") == "observe":
                try:
                    self._udp.sendto(json.dumps({
                        "ok": True, "nonce": msg.get("nonce"),
                        "addr": [addr[0], addr[1]],
                    }).encode(), addr)
                except OSError:
                    pass

    def _accept_loop(self) -> None:
        assert self._server is not None
        while not self._closed.is_set():
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            threading.Thread(target=self._handle_conn, args=(conn,), daemon=True).start()

    def _handle_conn(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(ACCEPT_TIMEOUT_S)
            msg = recv_json_frame(conn)
            if msg is None:
                conn.close()
                return
            # Failpoint: the relay control plane. ``drop`` discards the
            # control frame and closes the connection (the client sees a
            # dead relay and falls back to direct/punch paths); ``error``
            # answers a well-formed refusal; ``raise`` rides the except
            # below (connection closed, relay keeps serving others).
            act = failpoint("p2p.relay.control")
            if act is not None and act.kind in ("drop", "error"):
                if act.kind == "error":
                    send_json_frame(conn, {
                        "ok": False,
                        "error": act.msg or "injected fault"})
                conn.close()
                return
            mtype = msg.get("type")
            if mtype == RELAY_RESERVE:
                self._handle_reserve(conn, msg)
            elif mtype == RELAY_HOP:
                self._handle_hop(conn, msg)
            elif mtype == RELAY_ACCEPT:
                self._handle_accept(conn, msg)
            elif mtype == RELAY_PUNCH:
                self._handle_punch(conn, msg)
            else:
                send_json_frame(conn, {"ok": False, "error": "unknown type"})
                conn.close()
        except (OSError, ValueError, json.JSONDecodeError,
                FailpointError) as e:
            log.debug("relay conn error: %s", e)
            try:
                conn.close()
            except OSError:
                pass

    def _handle_reserve(self, conn: socket.socket, msg: dict) -> None:
        peer_id = str(msg.get("peer_id") or "")
        ts = str(msg.get("ts") or "")
        sig_hex = str(msg.get("sig") or "")
        try:
            pub = peer_id_to_public_key(peer_id)
            pub.verify(bytes.fromhex(sig_hex),
                       f"{RELAY_RESERVE}|{peer_id}|{ts}".encode())
        except Exception:
            send_json_frame(conn, {"ok": False, "error": "bad signature"})
            conn.close()
            return
        # Freshness window: the signature covers ts, so without this check a
        # captured RESERVE frame could be replayed forever to evict a peer's
        # live reservation and hijack its RELAY_INCOMING notifications.
        try:
            skew = abs(time.time() - float(ts))
        except ValueError:
            skew = float("inf")
        if skew > self.reserve_ts_window_s:
            send_json_frame(conn, {"ok": False, "error": "stale timestamp"})
            conn.close()
            return
        with self._mu:
            if (peer_id not in self._reservations
                    and len(self._reservations) >= self.max_reservations):
                send_json_frame(conn, {"ok": False, "error": "reservation limit"})
                conn.close()
                return
            old = self._reservations.get(peer_id)
            if old is not None:
                try:
                    old.sock.close()
                except OSError:
                    pass
            res = _Reservation(peer_id=peer_id, sock=conn)
            self._reservations[peer_id] = res
        send_json_frame(conn, {"ok": True})
        log.info("reservation: %s", peer_id[:12])
        conn.settimeout(None)
        # Bound *sends* on the control channel (SO_SNDTIMEO is send-only, so
        # the blocking recv loop below is unaffected): a peer that stops
        # reading can otherwise wedge the sweep ping or a HOP's
        # RELAY_INCOMING forever once the OS send buffer fills.
        conn.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                        struct.pack("ll", int(ACCEPT_TIMEOUT_S), 0))
        # Keep reading the control channel (pongs, punch acks, detect
        # close).
        try:
            while not self._closed.is_set():
                m = recv_json_frame(conn)
                if m is None:
                    break
                res.last_seen = time.time()
                if m.get("type") == RELAY_PUNCH_ACK:
                    with self._mu:
                        pp = self._pending_punch.get(
                            str(m.get("punch_id") or ""))
                    if pp is not None:
                        pp.target_udp = m.get("udp_addr")
                        pp.event.set()
        except (OSError, ValueError, json.JSONDecodeError):
            pass
        with self._mu:
            if self._reservations.get(peer_id) is res:
                del self._reservations[peer_id]
        try:
            conn.close()
        except OSError:
            pass
        log.info("reservation closed: %s", peer_id[:12])

    def _sweep_loop(self) -> None:
        """Ping every reservation periodically; evict those whose control
        channel has been silent past ``stale_after_s`` (the pong a live node
        sends back refreshes ``last_seen`` in the reserve read loop)."""
        while not self._closed.wait(self.sweep_interval_s):
            now = time.time()
            with self._mu:
                entries = list(self._reservations.items())
            for peer_id, res in entries:
                if now - res.last_seen > self.stale_after_s:
                    with self._mu:
                        if self._reservations.get(peer_id) is res:
                            del self._reservations[peer_id]
                    try:
                        res.sock.close()
                    except OSError:
                        pass
                    log.info("evicted stale reservation: %s", peer_id[:12])
                    continue
                # Bounded lock acquire: a sender already wedged on this
                # reservation must not stall sweeping of the others.
                if not res.send_lock.acquire(timeout=2.0):
                    continue
                try:
                    send_json_frame(res.sock, {"type": RELAY_PING})
                except OSError:
                    pass    # read loop will notice the dead socket
                finally:
                    res.send_lock.release()

    def _handle_hop(self, conn: socket.socket, msg: dict) -> None:
        target = str(msg.get("target") or "")
        with self._mu:
            res = self._reservations.get(target)
            if res is None:
                send_json_frame(conn, {"ok": False, "error": "no reservation for target"})
                conn.close()
                return
            if self._active_circuits >= self.max_circuits:
                send_json_frame(conn, {"ok": False, "error": "circuit limit"})
                conn.close()
                return
            conn_id = uuid.uuid4().hex
            pending = _PendingCircuit(dialer_sock=conn)
            self._pending[conn_id] = pending
        try:
            with res.send_lock:
                send_json_frame(res.sock, {"type": RELAY_INCOMING, "conn_id": conn_id})
        except OSError:
            with self._mu:
                self._pending.pop(conn_id, None)
            send_json_frame(conn, {"ok": False, "error": "target unreachable"})
            conn.close()
            return
        if not pending.event.wait(ACCEPT_TIMEOUT_S):
            with self._mu:
                self._pending.pop(conn_id, None)
            send_json_frame(conn, {"ok": False, "error": "target did not accept"})
            conn.close()
            return
        assert pending.target_sock is not None
        send_json_frame(conn, {"ok": True})
        self._splice(conn, pending.target_sock)

    def _handle_punch(self, conn: socket.socket, msg: dict) -> None:
        """Hole-punch coordination: forward the dialer's observed UDP
        endpoint to the target's control channel, wait for the target's
        ack carrying ITS observed endpoint, and return it to the dialer.
        The relay carries only this exchange — the handshake and message
        bytes then flow directly between the peers' UDP sockets."""
        target = str(msg.get("target") or "")
        udp_addr = _valid_udp_addr(msg.get("udp_addr"))
        if udp_addr is None:
            send_json_frame(conn, {"ok": False, "error": "bad udp_addr"})
            conn.close()
            return
        with self._mu:
            res = self._reservations.get(target)
            if res is None:
                send_json_frame(conn, {"ok": False,
                                       "error": "no reservation for target"})
                conn.close()
                return
            punch_id = uuid.uuid4().hex
            pending = _PendingPunch()
            self._pending_punch[punch_id] = pending
        try:
            with res.send_lock:
                send_json_frame(res.sock, {
                    "type": RELAY_PUNCH, "punch_id": punch_id,
                    "udp_addr": list(udp_addr),
                })
            if not pending.event.wait(ACCEPT_TIMEOUT_S):
                send_json_frame(conn, {"ok": False,
                                       "error": "target did not punch"})
                conn.close()
                return
            # A null/invalid ack addr is the target saying "I cannot
            # punch" — fail the dialer fast so it falls back to the
            # circuit instead of burning its handshake budget.
            target_udp = _valid_udp_addr(pending.target_udp)
            if target_udp is None:
                send_json_frame(conn, {"ok": False,
                                       "error": "target cannot punch"})
                conn.close()
                return
            send_json_frame(conn, {"ok": True,
                                   "udp_addr": list(target_udp)})
            conn.close()
        except OSError:
            try:
                conn.close()
            except OSError:
                pass
        finally:
            with self._mu:
                self._pending_punch.pop(punch_id, None)

    def _handle_accept(self, conn: socket.socket, msg: dict) -> None:
        conn_id = str(msg.get("conn_id") or "")
        with self._mu:
            pending = self._pending.pop(conn_id, None)
        if pending is None:
            send_json_frame(conn, {"ok": False, "error": "unknown conn_id"})
            conn.close()
            return
        send_json_frame(conn, {"ok": True})
        pending.target_sock = conn
        pending.event.set()

    def _splice(self, a: socket.socket, b: socket.socket) -> None:
        """Bidirectional byte pump between dialer and target sockets.

        Data plane goes native when buildable: one blocking C++
        poll-loop call per circuit (native/net_splice.cc — ctypes
        releases the GIL for its duration) instead of two Python
        recv/sendall threads serialising relayed bytes on the GIL. Same
        idle-timeout and half-close semantics either way."""
        with self._mu:
            self._active_circuits += 1
            self._n_spliced += 1
        lib = native.load("net_splice")
        if lib is not None:
            import ctypes
            lib.splice_pair.restype = ctypes.c_int64
            lib.splice_pair.argtypes = [ctypes.c_int, ctypes.c_int,
                                        ctypes.c_int]
            try:
                lib.splice_pair(a.fileno(), b.fileno(),
                                int(CIRCUIT_IDLE_TIMEOUT_S * 1000))
            finally:
                for s in (a, b):
                    try:
                        s.close()
                    except OSError:
                        pass
                with self._mu:
                    self._active_circuits -= 1
            return

        def pump(src: socket.socket, dst: socket.socket) -> None:
            try:
                src.settimeout(CIRCUIT_IDLE_TIMEOUT_S)
                while True:
                    data = src.recv(65536)
                    if not data:
                        break
                    dst.sendall(data)
            except OSError:
                pass
            finally:
                for s in (src, dst):
                    try:
                        s.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass

        t1 = threading.Thread(target=pump, args=(a, b), daemon=True)
        t2 = threading.Thread(target=pump, args=(b, a), daemon=True)
        t1.start(); t2.start()
        t1.join(); t2.join()
        for s in (a, b):
            try:
                s.close()
            except OSError:
                pass
        with self._mu:
            self._active_circuits -= 1


def main() -> None:
    svc = RelayService().start()
    # Machine-readable multiaddr hand-off for launchers: the identity (and
    # so the /p2p/<id> in the multiaddr) is fresh per start, so orchestrators
    # can't know it in advance — RELAY_ADDR_FILE names a file to publish it
    # in (start_all.py uses this to set RELAY_ADDRS on the nodes).
    addr_file = env_or("RELAY_ADDR_FILE", "")
    if addr_file:
        tmp = addr_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(svc.addr()))
        os.replace(tmp, addr_file)
    threading.Event().wait()


if __name__ == "__main__":
    main()
