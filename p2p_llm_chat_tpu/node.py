"""Per-user P2P chat node daemon.

Reference: go/cmd/node/main.go. One process per user composing:

- a P2P host with a chat stream handler on ``/p2p-llm-chat/1.0.0``
  (ChatProtocolID, main.go:48; handler main.go:158-172),
- an in-memory append-only Inbox (main.go:97-128),
- a DirectoryClient that registers on startup — fatal on failure
  (main.go:183-184) — and resolves recipients on send (main.go:225),
- a local HTTP API for the UI: ``POST /send`` (main.go:219-265),
  ``GET /inbox?after=`` (main.go:267-270), ``GET /me`` (main.go:272-278).

Env config (exact names from main.go:131-134): ``MYNAMEIS``, ``HTTP_ADDR``,
``DIRECTORY_URL``, ``BOOTSTRAP_ADDRS``; additive: ``P2P_ADDR`` (p2p listen
address, default 127.0.0.1:0), ``RELAY_ADDRS`` (comma-separated relay
multiaddrs to hold reservations on — the reference ships a relay daemon but
never wires it into the node, SURVEY.md §2 C6), ``IDENTITY_FILE`` (persist
the keypair; reference regenerates per start, README.md:134).

Deliberate fix (documented surface change): ``GET /me`` returns the base58
peer id string — the reference returns raw peer-ID bytes there
(``string(h.ID())``, main.go:275), an acknowledged quirk (SURVEY.md §2).

Directory resilience (additive — the directory is the acknowledged single
point of failure, reference README.md:135): successful lookups are cached
and served stale when the directory is down, so peers that have already
talked keep exchanging messages through an outage; and the node
re-registers on a background interval with exponential backoff
(``NODE_REREGISTER_S``, default 30 s, 0 disables), so a restarted
directory — it is in-memory, losing every record (SURVEY.md §2 C5) —
relearns the node without operator action. Startup registration stays
fatal-on-failure (main.go:184 parity).

DHT rung (additive): the reference constructs a kad-DHT it never routes
with (go/cmd/node/main.go:151, errors non-fatal at :153). Here the
from-scratch Kademlia (p2p/dht.py) is the THIRD rung of the lookup
ladder — directory -> cached record -> DHT — so never-before-paired
peers still resolve each other through a directory outage. The node
publishes its signed address record to the DHT on registration and on
every re-register tick. Env: ``DHT_ADDR`` (UDP listen, default
``127.0.0.1:0``; ``off`` disables), ``DHT_BOOTSTRAP`` (comma-separated
``host:port`` seeds). All DHT failures are non-fatal (reference :153
parity); ``GET /me`` exposes ``dht_addr`` so deployments can chain
bootstrap seeds without extra config.

NAT-PMP (parity with ``libp2p.NATPortMap()``, main.go:143): on by
default, best-effort, background — maps the p2p listen port on the
gateway (p2p/natpmp.py, RFC 6886) and advertises the external address
in directory/DHT records; renews at half-lifetime from the re-register
loop; releases on stop. ``NATPMP=0`` disables, ``NATPMP_GATEWAY``
overrides gateway discovery.

At-least-once delivery (additive — the reference tries each addr once
and drops the message, SURVEY.md §2 C5): every outgoing message carries
a sender-minted ``msg_id`` (proto.mint_msg_id) and the chat stream
grows an ack frame — the receiver acks after the inbox push, dedups
redelivered copies by ``msg_id``, and the sender parks unacked messages
in a bounded per-recipient **Outbox** (``P2P_OUTBOX_MAX`` messages per
peer, ``P2P_OUTBOX_TTL_S`` seconds). A redelivery worker retries on the
utils/backoff jittered schedule and RE-RESOLVES the recipient each
round (directory first, then the DHT rung — a queued message usually
means the peer moved or restarted, so stale addrs must refresh before
the next dial). ``POST /send`` answers ``{"status": "queued"}`` when
the peer is down instead of a 502, and ``stop()`` attempts one final
outbox flush, then deregisters from the directory (the DHT record
expires via its own TTL). Peers that predate the ack frame close the
stream without answering — EOF counts as legacy-delivered, keeping the
wire compatible in both directions. Drops (TTL lapse, overflow) are
counted on ``GET /metrics`` as ``p2p_messages_dropped_total``;
docs/robustness.md §Peer lifecycle has the state machine.
"""

from __future__ import annotations

import collections
import threading
import time
import uuid
from typing import Optional

from .directory import DirectoryClient
from .inbox import Inbox
from .obs import trace as _trace
from .utils.backoff import Backoff
from .p2p import Identity, Multiaddr, P2PHost
from .p2p.dht import DHTNode, parse_seeds
from .p2p.natpmp import PortMapper
from .p2p.transport import SecureStream
from .proto import ChatMessage, ack_frame, mint_msg_id, now_rfc3339, parse_ack
from .utils import failpoints as _fp
from .utils.env import env_float, env_int, env_or
from .utils.failpoints import failpoint
from .utils.http import HttpServer, Request, Response, Router
from .utils.log import get_logger
from .utils.metrics import Registry

log = get_logger("node")

CHAT_PROTOCOL_ID = "/p2p-llm-chat/1.0.0"   # go/cmd/node/main.go:48


class Outbox:
    """Bounded per-recipient queue of sent-but-unacked messages.

    Locking: ``_mu`` guards the tables and is NEVER held across network
    I/O — the redelivery worker snapshots under the lock, dials
    unlocked, then removes delivered entries under the lock again.
    Rounds themselves are serialized by the node's ``_flush_mu``, so a
    message is never dialed twice concurrently (and even a duplicate
    dial is idempotent at the receiver via msg_id dedup).
    """

    def __init__(self, max_per_peer: int, ttl_s: float) -> None:
        self.max_per_peer = max(1, max_per_peer)
        self.ttl_s = ttl_s
        self._mu = threading.Lock()
        # recipient -> deque[(msg, enqueued_at_monotonic)], send order
        self._pending: dict[str, collections.deque] = {}  # guarded-by: _mu

    def put(self, msg: ChatMessage) -> list[ChatMessage]:
        """Enqueue for redelivery; returns the OLDEST entries dropped to
        make room at the per-peer bound (overflow accounting)."""
        dropped: list[ChatMessage] = []
        with self._mu:
            q = self._pending.setdefault(msg.to_user, collections.deque())
            while len(q) >= self.max_per_peer:
                dropped.append(q.popleft()[0])
            q.append((msg, time.monotonic()))
        return dropped

    def expire(self, now: float) -> list[ChatMessage]:
        """Drop entries older than ``ttl_s``; returns them (TTL
        accounting). The queue head is the oldest, so one front-scan per
        recipient suffices."""
        out: list[ChatMessage] = []
        with self._mu:
            for user in list(self._pending):
                q = self._pending[user]
                while q and now - q[0][1] > self.ttl_s:
                    out.append(q.popleft()[0])
                if not q:
                    del self._pending[user]
        return out

    def snapshot(self) -> dict[str, list[tuple[ChatMessage, float]]]:
        with self._mu:
            return {u: list(q) for u, q in self._pending.items()}

    def remove(self, user: str, msg_id: str) -> Optional[float]:
        """Remove a delivered message; returns its enqueue time (for the
        delivery-latency observation), or None when already gone."""
        with self._mu:
            q = self._pending.get(user)
            if not q:
                return None
            for i, (m, t0) in enumerate(q):
                if m.msg_id == msg_id:
                    del q[i]
                    if not q:
                        del self._pending[user]
                    return t0
        return None

    def depth(self) -> int:
        with self._mu:
            return sum(len(q) for q in self._pending.values())

    def has(self, user: str) -> bool:
        with self._mu:
            return bool(self._pending.get(user))


class ChatNode:
    def __init__(
        self,
        username: Optional[str] = None,
        http_addr: Optional[str] = None,
        directory_url: Optional[str] = None,
        bootstrap_addrs: Optional[str] = None,
        p2p_addr: Optional[str] = None,
        relay_addrs: Optional[str] = None,
        identity_file: Optional[str] = None,
        inbox_cap: Optional[int] = None,
        dht_addr: Optional[str] = None,
        dht_bootstrap: Optional[str] = None,
    ) -> None:
        # Eager FAIL_POINTS parse: malformed chaos config fails at boot.
        from .utils.failpoints import load_env as load_failpoints_env
        load_failpoints_env()
        # Env-var defaults keep the reference's exact config surface
        # (go/cmd/node/main.go:131-134).
        self.username = username if username is not None else env_or("MYNAMEIS", "anon")
        self.http_addr = http_addr if http_addr is not None else env_or("HTTP_ADDR", ":8081")
        self.directory_url = (directory_url if directory_url is not None
                              else env_or("DIRECTORY_URL", "http://127.0.0.1:8080"))
        self.bootstrap_addrs = (bootstrap_addrs if bootstrap_addrs is not None
                                else env_or("BOOTSTRAP_ADDRS", ""))
        self.relay_addrs = (relay_addrs if relay_addrs is not None
                            else env_or("RELAY_ADDRS", ""))
        p2p_listen = p2p_addr if p2p_addr is not None else env_or("P2P_ADDR", "127.0.0.1:0")
        ident = Identity.load_or_generate(
            identity_file if identity_file is not None else env_or("IDENTITY_FILE", "") or None
        )
        self.host = P2PHost(identity=ident, listen_addr=p2p_listen)
        self.inbox = Inbox(max_messages=inbox_cap)
        self.dir = DirectoryClient(self.directory_url)
        dht_addr = dht_addr if dht_addr is not None else env_or("DHT_ADDR", "127.0.0.1:0")
        self.dht: Optional[DHTNode] = None
        if dht_addr.lower() not in ("off", "0", ""):
            try:
                self.dht = DHTNode(ident, dht_addr)
            except (ValueError, OSError) as e:
                # Bad addr / port taken: degrade, don't crash — every DHT
                # failure is non-fatal (go/cmd/node/main.go:153 parity).
                log.warning("DHT disabled: cannot bind %r (%s)", dht_addr, e)
        self.dht_bootstrap = (dht_bootstrap if dht_bootstrap is not None
                              else env_or("DHT_BOOTSTRAP", ""))
        # NAT-PMP port mapping (libp2p.NATPortMap() parity, main.go:143):
        # on by default like the reference, best-effort — no cooperative
        # gateway just means punch/relay carry reachability instead.
        # NATPMP=0 disables; NATPMP_GATEWAY=host[:port] overrides discovery
        # (used by tests to point at a fake gateway).
        self._natpmp_enabled = env_or("NATPMP", "1") not in ("0", "off", "")
        self._natpmp_gateway = env_or("NATPMP_GATEWAY", "")
        self._mapper: Optional[PortMapper] = None
        self._nat_ext: Optional[tuple[str, int]] = None
        self.reregister_s = float(env_or("NODE_REREGISTER_S", "30"))
        self._lookup_cache: dict[str, object] = {}
        self._cache_mu = threading.Lock()
        self._closed = threading.Event()
        # At-least-once delivery state (module docstring): the unacked
        # outbox, the per-sender msg_id sequence, and the drop ledger.
        self.outbox = Outbox(env_int("P2P_OUTBOX_MAX", 128),
                             env_float("P2P_OUTBOX_TTL_S", 300.0))
        self._outbox_kick = threading.Event()
        # Serializes redelivery rounds (worker tick vs stop()'s final
        # flush). Held across dials BY DESIGN — it is a round mutex, not
        # a data lock; outbox._mu nests strictly inside it.
        self._flush_mu = threading.Lock()
        self._seq_mu = threading.Lock()
        self._send_seq = 0                       # guarded-by: _seq_mu
        # Per-boot salt for msg_id minting: _send_seq restarts at 0
        # with the process, so ids must carry a per-incarnation nonce
        # or a post-restart send repeating an earlier (seq, content)
        # pair would re-mint an old id and get dedup-suppressed by a
        # receiver that stayed up (silent loss of a NEW message).
        self._boot_nonce = uuid.uuid4().hex
        self._drop_mu = threading.Lock()
        self._dropped = {"ttl": 0, "overflow": 0}  # guarded-by: _drop_mu
        self.metrics = Registry()
        self._m_outbox_depth = self.metrics.gauge("p2p_outbox_depth")
        self._m_redelivered = self.metrics.counter("p2p_redelivered_total")
        self._m_dedup = self.metrics.counter("p2p_dedup_suppressed_total")
        self._m_delivery_ms = self.metrics.histogram("p2p_delivery_ms")
        self._http: Optional[HttpServer] = None
        self.router = Router()
        self.router.add("POST", "/send", self._handle_send)
        self.router.add("GET", "/inbox", self._handle_inbox)
        self.router.add("GET", "/me", self._handle_me)
        self.router.add("GET", "/metrics", self._handle_metrics)
        self.router.add("GET", "/healthz", lambda r: Response(200, {"status": "ok"}))
        # grafttrace (obs/trace.py): /send is a chat-plane INGRESS — it
        # parses or mints a trace context per message and records the
        # node.send span (lookup ladder + delivery, with the winning
        # leg's via=direct|relay meta). Same bounded store + listing
        # contract as the serve fronts.
        self.trace = _trace.TraceStore(replica=f"node:{self.username}")
        self.router.add("GET", "/admin/trace", self._handle_trace)

    # -- p2p side ------------------------------------------------------------

    def _on_chat_stream(self, stream: SecureStream, remote_peer_id: str) -> None:
        """Inbound chat message: read whole stream until the sender half-
        closes, parse one JSON ChatMessage, push to inbox
        (go/cmd/node/main.go:158-172). Messages carrying a ``msg_id``
        get an ack frame back on the same (full-duplex) stream AFTER the
        inbox push — a redelivered duplicate is suppressed by the inbox
        but STILL acked, because the original delivery already won and
        the sender only needs to stop retrying."""
        try:
            raw = stream.read_all()
            if not raw:
                return
            msg = ChatMessage.from_json(raw)
            fresh = self.inbox.push(msg)
            if fresh:
                log.info("inbox <- %s: %r (from peer %s)",
                         msg.from_user, msg.content[:60], remote_peer_id[:12])
            else:
                self._m_dedup.inc()
                log.info("dedup: suppressed duplicate %s from %s",
                         msg.msg_id[:12], msg.from_user)
            if msg.msg_id:
                stream.send_frame(ack_frame(msg.msg_id))
        except (ValueError, OSError) as e:
            log.warning("bad chat stream from %s: %s", remote_peer_id[:12], e)
        finally:
            stream.close()

    # -- HTTP API ------------------------------------------------------------

    def _handle_send(self, req: Request) -> Response:
        """POST /send {to_username, content} (go/cmd/node/main.go:219-265)."""
        try:
            body = req.json() or {}
        except ValueError:
            return Response(400, {"error": "invalid json"})
        to_username = str(body.get("to_username") or "")
        content = str(body.get("content") or "")
        if not to_username or not content:
            return Response(400, {"error": "to_username and content required"})

        # node.send covers the whole send path — lookup ladder + the
        # delivery walk — and its ``via`` meta names the winning leg
        # (relay vs direct), so a relay-path SLO breach attributes to
        # the p2p phase with the leg visible. The trace id echoes in
        # the response so a client (loadgen) can fetch the timeline.
        tctx = _trace.parse_header(req.headers.get(_trace.HEADER_LC))
        if tctx is None:
            tctx = _trace.mint()
        t_send = time.monotonic()

        def _span(**meta) -> None:
            if tctx.sampled:
                self.trace.add(tctx.trace_id, "node.send", t_send,
                               time.monotonic() - t_send,
                               to=to_username, **meta)

        from_cache = False
        via_dht = False
        try:
            rec = self.dir.lookup(to_username)          # main.go:225
            with self._cache_mu:
                self._lookup_cache[to_username] = rec
        except Exception as e:
            # Directory down or record missing: fall back to the last
            # known-good record so peers that have already talked keep
            # talking through a directory outage (README.md:135 names the
            # directory as the single point of failure; this removes it
            # from the send path for warm pairs).
            with self._cache_mu:
                rec = self._lookup_cache.get(to_username)
            if rec is not None:
                from_cache = True
                log.warning("directory lookup for %s failed (%s); using "
                            "cached record", to_username, e)
            elif self.dht is not None:
                # Third rung: never-paired peers resolve via the DHT's
                # signed records while the directory is down (the cache
                # rung only covers peers we've already talked to). The
                # lookup runs inline on the /send handler thread, so it
                # carries a wall-time budget: with a routing table full
                # of dead contacts the alpha-rounds of UDP timeouts must
                # not hold the UI's send for many seconds (ADVICE r4).
                rec = self.dht.get_record(to_username, budget_s=3.0)
                if rec is not None:
                    log.warning("directory lookup for %s failed (%s); "
                                "resolved via DHT", to_username, e)
                    via_dht = True
            if rec is None and not self.outbox.has(to_username):
                return Response(404, {"error": f"lookup failed: {e}"})
            # rec None but the outbox holds queued messages for this
            # user: the recipient exists and is mid-churn (e.g. they
            # deregistered on shutdown and the first queued send spent
            # the cached record) — this send JOINS the queue instead of
            # 404ing, preserving order behind the already-parked ones.

        with self._seq_mu:
            self._send_seq += 1
            seq = self._send_seq
        msg = ChatMessage(from_user=self.username, to_user=to_username,
                          content=content, timestamp=now_rfc3339(),
                          msg_id=mint_msg_id(self.username, seq, content,
                                             nonce=self._boot_nonce))

        if self.outbox.has(to_username):
            # A backlog is already parked for this recipient (the peer
            # just came back but the worker hasn't flushed yet, or is
            # mid-flush): delivering the fresh message directly would
            # jump ahead of the queued ones — _flush_outbox stops at
            # the first failure per recipient precisely to preserve
            # send order. Join the back of the queue and kick the
            # worker so the whole backlog drains in order.
            for old in self.outbox.put(msg):
                self._note_drop("overflow", old)
            self._m_outbox_depth.set(self.outbox.depth())
            self._outbox_kick.set()
            _span(outcome="queued", attempts=0)
            return Response(200, {"status": "queued", "id": msg.id,
                                  "msg_id": msg.msg_id,
                                  "trace": tctx.trace_id})

        errors: list[str] = []
        won = self._deliver(rec, msg, errors) if rec is not None else ""
        if won:
            if via_dht:
                # Cache only after a delivery proves the record good — a
                # dead DHT record must not poison the cache rung.
                with self._cache_mu:
                    self._lookup_cache[to_username] = rec
            self._m_delivery_ms.observe((time.monotonic() - t_send) * 1000.0)
            _span(via=("relay" if "/p2p-circuit/" in won else "direct"))
            return Response(200, {"status": "sent", "id": msg.id,
                                  "trace": tctx.trace_id})  # main.go:264

        # The cached record may be stale (the peer moved while the
        # directory was down). If the DHT holds a record with different
        # addrs, try those before giving up — it is republished every
        # re-register tick, so it tracks moves the cache cannot.
        if from_cache and self.dht is not None:
            # Same wall-time budget as the third-rung lookup above: this
            # retry also runs inline on the /send handler thread.
            fresh = self.dht.get_record(to_username, budget_s=3.0)
            if fresh is not None and fresh.peer_id != rec.peer_id:
                # Identity pinning: for a peer we already hold a binding
                # for, a DHT record signed by a DIFFERENT identity is a
                # username squat, not a move — refuse it. (Never-paired
                # resolution has no prior binding and is trust-on-first-
                # use, the same model as the reference's unauthenticated
                # directory.)
                log.warning("DHT record for %s signed by a different "
                            "identity; ignoring", to_username)
                fresh = None
            if fresh is not None and set(fresh.addrs) != set(rec.addrs):
                log.warning("cached addrs for %s are dead; retrying via "
                            "DHT record", to_username)
                won = self._deliver(fresh, msg, errors)
                if won:
                    with self._cache_mu:
                        self._lookup_cache[to_username] = fresh
                    self._m_delivery_ms.observe(
                        (time.monotonic() - t_send) * 1000.0)
                    _span(via=("relay" if "/p2p-circuit/" in won
                               else "direct"))
                    return Response(200, {"status": "sent", "id": msg.id,
                                          "trace": tctx.trace_id})
        if from_cache:
            # Total failure on a cached record: drop it so the next send
            # re-resolves instead of re-dialing dead addrs forever.
            with self._cache_mu:
                self._lookup_cache.pop(to_username, None)
        # At-least-once: the peer is unreachable RIGHT NOW — park the
        # message in the outbox and let the redelivery worker retry on
        # the backoff schedule, re-resolving each round. The client gets
        # a well-formed queued answer instead of the reference's
        # 502-and-forget (SURVEY.md §2 C5 message loss).
        for old in self.outbox.put(msg):
            self._note_drop("overflow", old)
        self._m_outbox_depth.set(self.outbox.depth())
        self._outbox_kick.set()
        _span(outcome="queued", attempts=len(errors))
        return Response(200, {"status": "queued", "id": msg.id,
                              "msg_id": msg.msg_id, "trace": tctx.trace_id})

    def _deliver(self, rec, msg: ChatMessage, errors: list[str]) -> str:
        """Try each advertised addr (direct first, then circuits), one stream
        per message, write JSON, half-close, await the ack
        (main.go:235-261 plus the at-least-once wire). Returns the
        addr that delivered (truthy — callers keep their boolean
        checks; the trace span reads the relay marker off it), or ""
        when every addr failed."""
        addrs = sorted(rec.addrs, key=lambda a: "/p2p-circuit/" in a)
        for addr_str in addrs:
            try:
                # Chaos: a raised/error'd/dropped deliver fails THIS
                # attempt — the message falls through to the outbox and
                # the redelivery worker (docs/robustness.md contract).
                act = failpoint("p2p.node.deliver")
                if act is not None:
                    raise ConnectionError(
                        f"failpoint p2p.node.deliver ({act.kind})")
                maddr = Multiaddr.parse(addr_str)
                if maddr.peer_id is None:
                    maddr = maddr.with_peer(rec.peer_id)
                stream = self.host.new_stream(maddr, CHAT_PROTOCOL_ID, timeout=5.0)
                try:
                    stream.send_frame(msg.to_json())
                    stream.close_write()
                    if msg.msg_id:
                        # At-least-once wire: wait for the receiver's
                        # ack frame. None (EOF without a frame) is a
                        # pre-ack peer closing after the read — count it
                        # delivered (legacy wire compat); a frame that
                        # is not OUR ack is a broken peer.
                        stream.settimeout(5.0)
                        raw = stream.recv_frame()
                        if raw is not None and parse_ack(raw) != msg.msg_id:
                            raise ConnectionError("bad delivery ack")
                finally:
                    stream.close()
                return addr_str
            except Exception as e:  # noqa: BLE001 — collect and try next addr
                errors.append(f"{addr_str}: {e}")
        return ""

    def _note_drop(self, reason: str, msg: ChatMessage) -> None:
        """Account an outbox drop (`reason` = ttl|overflow) — the churn
        contract's loss ledger (a nonzero count under plain churn is a
        contract breach; docs/loadtest.md peer_churn)."""
        with self._drop_mu:
            self._dropped[reason] += 1
        log.warning("outbox dropped %s -> %s (%s)",
                    (msg.msg_id or msg.id)[:12], msg.to_user, reason)

    def _handle_metrics(self, req: Request) -> Response:
        """GET /metrics: the chat-plane delivery ledger (Prometheus
        text), same exposition contract as the serve fronts."""
        self._m_outbox_depth.set(self.outbox.depth())
        text = self.metrics.render()
        with self._drop_mu:
            drops = dict(self._dropped)
        text += "# TYPE p2p_messages_dropped_total counter\n" + "".join(
            f'p2p_messages_dropped_total{{reason="{r}"}} {n}\n'
            for r, n in sorted(drops.items()))
        hits = _fp.snapshot()
        if hits:
            # Same operator alarm as the serve front: ANY
            # failpoint_hits_total series in a production scrape means
            # chaos is armed on this node.
            text += "# TYPE failpoint_hits_total counter\n" + "".join(
                f'failpoint_hits_total{{site="{site}"}} {n}\n'
                for site, n in sorted(hits.items()))
        return Response(200, text, content_type="text/plain; version=0.0.4")

    def _handle_trace(self, req: Request) -> Response:
        """GET /admin/trace[?id=]: the node's span store — same listing
        contract as the serve fronts (serve/api.py _trace_list), so one
        client-side fetch loop reads any plane's timelines."""
        tid = str(req.query.get("id") or "")
        if tid:
            spans = self.trace.get(tid)
            if not spans:
                return Response(404, {"error": f"trace {tid!r} not held"})
            return Response(200, {"id": tid, "spans": spans})
        # Stats nest under their own key: the store's stats() also
        # counts "traces" and would clobber the id list if splatted.
        return Response(200, {"traces": self.trace.ids(),
                              "stats": self.trace.stats()})

    def _handle_inbox(self, req: Request) -> Response:
        """GET /inbox?after=<id> (go/cmd/node/main.go:267-270)."""
        after = req.query.get("after", "")
        return Response(200, [m.to_dict() for m in self.inbox.drain(after)])

    def _handle_me(self, req: Request) -> Response:
        """GET /me (go/cmd/node/main.go:272-278). Returns the base58 peer id
        (deliberate fix of the raw-bytes quirk at main.go:275) plus addrs."""
        out = {
            "username": self.username,
            "peer_id": self.host.peer_id,
            "addrs": [str(a) for a in self.host.addrs()],
        }
        if self.dht is not None:
            dht_host, dht_port = self.dht.addr
            if dht_host in ("0.0.0.0", "::"):
                # A wildcard bind is not dialable — substitute the host's
                # advertise address so seed chaining works cross-host.
                dht_host = self.host.advertise_host
            out["dht_addr"] = f"{dht_host}:{dht_port}"
        return Response(200, out)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ChatNode":
        self.host.set_stream_handler(CHAT_PROTOCOL_ID, self._on_chat_stream)
        self.host.start()

        # Relay reservations (additive; see module docstring).
        for addr_str in filter(None, (s.strip() for s in self.relay_addrs.split(","))):
            self.host.reserve_on_relay(Multiaddr.parse(addr_str))

        # Register with the directory — fatal on failure, matching
        # go/cmd/node/main.go:184.
        addrs = [str(a) for a in self.host.addrs()]
        self.dir.register(self.username, self.host.peer_id, addrs)
        log.info("registered %s (%s) with directory %s",
                 self.username, self.host.peer_id[:12], self.directory_url)

        # DHT join + signed-record publish — every step non-fatal
        # (go/cmd/node/main.go:153 parity). The join runs on a background
        # thread: unreachable seeds cost seconds of UDP timeouts and must
        # not delay the HTTP API coming up.
        if self.dht is not None:
            self.dht.start()
            threading.Thread(target=self._dht_join, args=(addrs,),
                             daemon=True, name="dht-join").start()

        # NAT-PMP mapping — background (gateway retransmits cost seconds),
        # best-effort; a mapped external addr is re-advertised via the
        # re-register loop once acquired.
        if self._natpmp_enabled:
            threading.Thread(target=self._natpmp_setup, daemon=True,
                             name="natpmp").start()

        # Bootstrap connects: parse multiaddr -> connect; errors logged,
        # non-fatal (go/cmd/node/main.go:189-211).
        for addr_str in filter(None, (s.strip() for s in self.bootstrap_addrs.split(","))):
            try:
                pid = self.host.connect(Multiaddr.parse(addr_str))
                log.info("bootstrap connected to %s", pid[:12])
            except Exception as e:  # noqa: BLE001
                log.warning("bootstrap connect %s failed: %s", addr_str, e)

        if self.reregister_s > 0:
            threading.Thread(target=self._reregister_loop, daemon=True,
                             name="reregister").start()
        threading.Thread(target=self._redelivery_loop, daemon=True,
                         name="redelivery").start()

        self._http = HttpServer(self.router, self.http_addr).start()
        log.info("node %s HTTP API on %s", self.username, self._http.addr)
        return self

    def _dht_join(self, addrs: list[str]) -> None:
        """Background DHT bootstrap + initial record publish (start() must
        not block on UDP timeouts to dead seeds). The re-register loop
        republishes afterwards, so a failed initial publish self-heals."""
        try:
            seeds = parse_seeds(self.dht_bootstrap)
            if seeds:
                self.dht.bootstrap(seeds)
            self.dht.put_self_record(self.username, addrs)
        except Exception as e:  # noqa: BLE001
            log.warning("dht join/publish failed (non-fatal): %s", e)

    def _natpmp_setup(self) -> None:
        """Map the p2p listen port on the gateway and advertise the
        external addr (NATPortMap parity). Every failure degrades to
        punch/relay reachability."""
        try:
            gw_host, gw_port = None, 5351
            if self._natpmp_gateway:
                h, _, p = self._natpmp_gateway.rpartition(":")
                gw_host, gw_port = (h or self._natpmp_gateway,
                                    int(p) if h else 5351)
            mapper = PortMapper(self.host.listen_port,
                                gateway=gw_host, port=gw_port)
            if self._closed.is_set():
                return
            ext = mapper.acquire()
            # Assign BEFORE checking _closed: stop() sets _closed first and
            # checks _mapper second, so whichever thread loses the race
            # still sees the other's write and release() runs exactly once
            # (it is a no-op on an already-released mapping).
            self._mapper = mapper
            if self._closed.is_set():
                mapper.release()
                return
            if ext is None:
                return
            self._advertise_mapping(ext)
        except Exception as e:  # noqa: BLE001
            log.warning("NAT-PMP setup failed (non-fatal): %s", e)

    def _advertise_mapping(self, ext: tuple[str, int]) -> None:
        """(Re)advertise the NAT-mapped external addr and eagerly push the
        updated record to the directory + DHT."""
        if self._nat_ext is not None and self._nat_ext != ext:
            self.host.remove_advertised_addr(
                Multiaddr(self._nat_ext[0], self._nat_ext[1]))
        self._nat_ext = ext
        self.host.add_advertised_addr(Multiaddr(ext[0], ext[1]))
        addrs = [str(a) for a in self.host.addrs()]
        try:
            self.dir.register(self.username, self.host.peer_id, addrs)
        except Exception:  # noqa: BLE001 — reregister loop will retry
            pass
        if self.dht is not None:
            try:
                self.dht.put_self_record(self.username, addrs)
            except Exception:  # noqa: BLE001
                pass

    def _reregister_loop(self) -> None:
        """Periodically re-register so an (in-memory, record-losing)
        directory restart relearns this node; failures back off with
        jittered exponential delays up to 8x the interval (utils/backoff
        — the jitter keeps a fleet of nodes from hammering a restarted
        directory in lockstep) and never crash the node — only the
        STARTUP registration is fatal (main.go:184 parity). Failure logs
        are bounded to one WARNING per outage (state-change logging: an
        hours-long outage is one 'lost' line and one 'recovered' line,
        not one line per attempt)."""
        backoff = Backoff(base_s=self.reregister_s,
                          max_s=self.reregister_s * 8, jitter=0.25)
        dir_ok = True
        delay = self.reregister_s
        while not self._closed.wait(delay):
            try:
                # In a try: host sockets may be mid-close when stop()
                # races this tick, and the loop must survive it.
                addrs = [str(a) for a in self.host.addrs()]
            except Exception:  # noqa: BLE001
                continue
            try:
                self.dir.register(self.username, self.host.peer_id, addrs)
                if not dir_ok:
                    dir_ok = True
                    log.info("directory %s reachable again; re-registered",
                             self.directory_url)
                backoff.reset()
                delay = self.reregister_s
            except Exception as e:  # noqa: BLE001 — outage, keep trying
                delay = backoff.next()
                if dir_ok:
                    dir_ok = False
                    log.warning("re-register failed (%s); backing off "
                                "(next attempt in %.0fs, then "
                                "exponentially up to %.0fs)",
                                e, delay, self.reregister_s * 8)
                else:
                    log.debug("re-register still failing (%s); next "
                              "attempt in %.0fs", e, delay)
            # Renew the NAT-PMP mapping before it lapses (half-lifetime
            # cadence is tracked inside the mapper); a changed grant
            # (gateway reboot, reassigned port) is re-advertised so the
            # records track the LIVE external addr, not the original one.
            if self._mapper is not None:
                try:
                    changed = self._mapper.renew_if_due()
                    if changed is not None:
                        self._advertise_mapping(changed)
                except Exception as e:  # noqa: BLE001
                    log.debug("NAT-PMP renew failed: %s", e)
            # DHT republish runs even when the directory is down — that is
            # precisely when the DHT rung carries the lookups.
            if self.dht is not None:
                try:
                    # AFTER the directory register (dead-contact RPC
                    # timeouts here must not delay directory relearn):
                    # republish keeps the record alive past the DHT's TTL
                    # and re-seeds it onto nodes that joined since.
                    self.dht.put_self_record(self.username, addrs)
                except Exception as e:  # noqa: BLE001
                    log.debug("dht republish failed: %s", e)

    # -- at-least-once redelivery -------------------------------------------

    def _resolve_for_redelivery(self, to_username: str):
        """Re-resolve a queued recipient before a redelivery round:
        directory first (the fresh record — the peer most likely MOVED,
        which is why the message is queued), then the cache, then the
        DHT rung with the same identity pinning as the /send ladder.
        Returns None when no rung answers — the recipient stays queued
        and the round backs off."""
        # Chaos: a failed resolve leaves the whole recipient queued this
        # round — no crash, no message loss, retried on the backoff
        # schedule (docs/robustness.md contract).
        act = failpoint("p2p.node.resolve")
        if act is not None:
            return None
        try:
            rec = self.dir.lookup(to_username)
            with self._cache_mu:
                self._lookup_cache[to_username] = rec
            return rec
        except Exception:  # noqa: BLE001 — fall through the ladder
            pass
        with self._cache_mu:
            cached = self._lookup_cache.get(to_username)
        if self.dht is not None:
            fresh = self.dht.get_record(to_username, budget_s=3.0)
            if fresh is not None:
                if cached is not None and fresh.peer_id != getattr(
                        cached, "peer_id", None):
                    # Identity pinning (same rule as _handle_send): a
                    # record signed by a different identity is a squat,
                    # not a move — keep the pinned binding.
                    return cached
                return fresh
        return cached

    def _flush_outbox(self) -> bool:
        """One redelivery round: TTL-expire, then per recipient
        re-resolve and retry the queued messages in send order (stopping
        at the first failure per recipient, so order is preserved).
        Returns True when anything failed this round. Serialized by
        ``_flush_mu``; the outbox lock is never held across a dial."""
        with self._flush_mu:
            for old in self.outbox.expire(time.monotonic()):
                self._note_drop("ttl", old)
            any_failed = False
            for user, entries in self.outbox.snapshot().items():
                try:
                    rec = self._resolve_for_redelivery(user)
                except Exception as e:  # noqa: BLE001 — incl. armed raise
                    log.debug("redelivery resolve %s failed: %s", user, e)
                    rec = None
                if rec is None:
                    any_failed = True
                    continue
                for msg, t0 in entries:
                    errors: list[str] = []
                    if not self._deliver(rec, msg, errors):
                        any_failed = True
                        log.debug("redelivery %s -> %s failed: %s",
                                  msg.msg_id[:12], user, "; ".join(errors))
                        break
                    if self.outbox.remove(user, msg.msg_id) is not None:
                        self._m_redelivered.inc()
                        wait_s = time.monotonic() - t0
                        self._m_delivery_ms.observe(wait_s * 1000.0)
                        log.info("redelivered %s -> %s after %.1fs",
                                 msg.msg_id[:12], user, wait_s)
            self._m_outbox_depth.set(self.outbox.depth())
            return any_failed

    def _redelivery_loop(self) -> None:
        """Background worker: retries unacked messages on a jittered
        exponential schedule (utils/backoff — the jitter keeps a fleet
        of senders from dialing a restarted peer in lockstep). A /send
        that queues kicks the worker awake, so the first retry doesn't
        wait out an idle tick."""
        backoff = Backoff(base_s=0.25, max_s=4.0, jitter=0.5)
        delay = 0.25
        while True:
            self._outbox_kick.wait(timeout=delay)
            self._outbox_kick.clear()
            if self._closed.is_set():
                return
            if self.outbox.depth() == 0:
                backoff.reset()
                delay = 0.5       # idle: the kick event wakes us instantly
                continue
            try:
                failed = self._flush_outbox()
            except Exception as e:  # noqa: BLE001 — worker must survive
                log.warning("redelivery round failed: %s", e)
                failed = True
            delay = backoff.next() if failed else (backoff.reset() or 0.05)

    @property
    def http_url(self) -> str:
        assert self._http is not None
        return self._http.url

    def serve_forever(self) -> None:
        """Run as a daemon until SIGTERM/SIGINT, then clean up — the
        NAT-PMP mapping in particular must be released (a plain kill
        would leave the gateway forwarding the port for up to the
        mapping lifetime)."""
        import signal

        done = threading.Event()
        for signum in (signal.SIGTERM, signal.SIGINT):
            signal.signal(signum, lambda *_: done.set())
        self.start()
        done.wait()
        self.stop()

    def stop(self) -> None:
        self._closed.set()
        self._outbox_kick.set()     # unblock the worker so it exits
        if self._http:
            self._http.stop()
        # Graceful shutdown, while the p2p host is still up: one final
        # outbox flush (last chance for queued messages — _flush_mu
        # serializes against a worker round already in flight), then
        # deregister so the directory stops advertising a dead node
        # (the reference never deregisters — SURVEY.md §2 C5; the DHT
        # record is signed + TTL'd and expires on its own).
        if self.outbox.depth():
            try:
                self._flush_outbox()
            except Exception as e:  # noqa: BLE001 — best-effort flush
                log.debug("final outbox flush failed: %s", e)
        try:
            self.dir.deregister(self.username, self.host.peer_id)
            log.info("deregistered %s from directory %s (DHT record "
                     "expires via its own TTL)",
                     self.username, self.directory_url)
        except Exception as e:  # noqa: BLE001 — directory may be gone
            log.debug("directory deregister failed (non-fatal): %s", e)
        if self.dht is not None:
            self.dht.close()
        if self._mapper is not None:
            try:
                self._mapper.release()
            except Exception:  # noqa: BLE001 — best-effort cleanup
                pass
        self.host.close()


def main() -> None:
    ChatNode().serve_forever()


if __name__ == "__main__":
    main()
