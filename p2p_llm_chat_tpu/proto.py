"""Chat wire schema.

Reference: ``proto.ChatMessage`` (go/cmd/node/proto/message.go:23-29) — a
single struct with snake_case JSON tags, one JSON-encoded message per peer
stream. We keep the exact field names and JSON shape so directory records,
inbox payloads, and peer streams are wire-compatible with the reference.
"""

from __future__ import annotations

import hashlib
import json
import uuid
from dataclasses import dataclass, field
from datetime import datetime, timezone
from typing import Any, Optional


def now_rfc3339() -> str:
    """RFC3339/ISO-8601 UTC timestamp, the format Go's time.Time marshals to."""
    return datetime.now(timezone.utc).isoformat().replace("+00:00", "Z")


def parse_ts(ts: str) -> datetime:
    """Parse an RFC3339 timestamp, tolerating 'Z' suffix and missing tz.

    Mirrors the UI-side tolerant parser (web/streamlit_app.py:120-127): on
    failure callers should fall back to epoch ordering rather than crash.
    """
    try:
        if ts.endswith("Z"):
            ts = ts[:-1] + "+00:00"
        dt = datetime.fromisoformat(ts)
        if dt.tzinfo is None:
            dt = dt.replace(tzinfo=timezone.utc)
        return dt
    except (ValueError, AttributeError):
        return datetime.fromtimestamp(0, tz=timezone.utc)


def mint_msg_id(from_user: str, seq: int, content: str,
                nonce: str = "") -> str:
    """Sender-minted delivery identity: sha1 over sender + per-boot
    nonce + per-sender sequence + body. Stable across redelivery
    attempts of the SAME send (the dedup key for at-least-once
    delivery) while distinct sends of identical text still get
    distinct ids via ``seq``. ``nonce`` is a random per-process value
    (node.py mints one per boot): ``seq`` restarts at 0 with the
    process, so without it a post-restart send repeating an earlier
    (seq, content) pair would re-mint an old id and be silently
    dedup-suppressed by any receiver that stayed up."""
    h = hashlib.sha1()
    h.update(from_user.encode("utf-8"))
    h.update(b"\x00")
    h.update(nonce.encode("utf-8"))
    h.update(b"\x00")
    h.update(str(seq).encode("ascii"))
    h.update(b"\x00")
    h.update(content.encode("utf-8"))
    return h.hexdigest()


def ack_frame(msg_id: str) -> bytes:
    """The receiver's delivery acknowledgement, framed back on the same
    chat stream after the message is durably in the inbox. Peers that
    predate the ack (the reference wire) just close; the sender treats
    EOF as legacy-delivered, so the field stays wire-compatible."""
    return json.dumps({"ack": msg_id}).encode("utf-8")


def parse_ack(raw: bytes) -> Optional[str]:
    """Parse an ack frame; None for anything that isn't one."""
    try:
        d = json.loads(raw)
    except (ValueError, UnicodeDecodeError):
        return None
    if isinstance(d, dict) and isinstance(d.get("ack"), str):
        return d["ack"]
    return None


@dataclass
class ChatMessage:
    """One chat message. JSON keys match go/cmd/node/proto/message.go:23-29.

    ``msg_id`` is additive: a sender-minted delivery identity
    (``mint_msg_id``) used for redelivery dedup. It is omitted from the
    JSON when empty, so streams stay byte-compatible with the reference
    and with pre-msg_id peers in both directions.
    """

    id: str = field(default_factory=lambda: str(uuid.uuid4()))
    from_user: str = ""
    to_user: str = ""
    content: str = ""
    timestamp: str = field(default_factory=now_rfc3339)
    msg_id: str = ""

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {
            "id": self.id,
            "from_user": self.from_user,
            "to_user": self.to_user,
            "content": self.content,
            "timestamp": self.timestamp,
        }
        if self.msg_id:
            d["msg_id"] = self.msg_id
        return d

    def to_json(self) -> bytes:
        return json.dumps(self.to_dict()).encode("utf-8")

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ChatMessage":
        return cls(
            id=str(d.get("id", "")),
            from_user=str(d.get("from_user", "")),
            to_user=str(d.get("to_user", "")),
            content=str(d.get("content", "")),
            timestamp=str(d.get("timestamp", "")),
            msg_id=str(d.get("msg_id", "")),
        )

    @classmethod
    def from_json(cls, raw: bytes | str) -> "ChatMessage":
        d = json.loads(raw)
        if not isinstance(d, dict):
            raise ValueError("chat message must be a JSON object")
        return cls.from_dict(d)
