"""Chat wire schema.

Reference: ``proto.ChatMessage`` (go/cmd/node/proto/message.go:23-29) — a
single struct with snake_case JSON tags, one JSON-encoded message per peer
stream. We keep the exact field names and JSON shape so directory records,
inbox payloads, and peer streams are wire-compatible with the reference.
"""

from __future__ import annotations

import json
import uuid
from dataclasses import dataclass, field
from datetime import datetime, timezone
from typing import Any


def now_rfc3339() -> str:
    """RFC3339/ISO-8601 UTC timestamp, the format Go's time.Time marshals to."""
    return datetime.now(timezone.utc).isoformat().replace("+00:00", "Z")


def parse_ts(ts: str) -> datetime:
    """Parse an RFC3339 timestamp, tolerating 'Z' suffix and missing tz.

    Mirrors the UI-side tolerant parser (web/streamlit_app.py:120-127): on
    failure callers should fall back to epoch ordering rather than crash.
    """
    try:
        if ts.endswith("Z"):
            ts = ts[:-1] + "+00:00"
        dt = datetime.fromisoformat(ts)
        if dt.tzinfo is None:
            dt = dt.replace(tzinfo=timezone.utc)
        return dt
    except (ValueError, AttributeError):
        return datetime.fromtimestamp(0, tz=timezone.utc)


@dataclass
class ChatMessage:
    """One chat message. JSON keys match go/cmd/node/proto/message.go:23-29."""

    id: str = field(default_factory=lambda: str(uuid.uuid4()))
    from_user: str = ""
    to_user: str = ""
    content: str = ""
    timestamp: str = field(default_factory=now_rfc3339)

    def to_dict(self) -> dict[str, Any]:
        return {
            "id": self.id,
            "from_user": self.from_user,
            "to_user": self.to_user,
            "content": self.content,
            "timestamp": self.timestamp,
        }

    def to_json(self) -> bytes:
        return json.dumps(self.to_dict()).encode("utf-8")

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ChatMessage":
        return cls(
            id=str(d.get("id", "")),
            from_user=str(d.get("from_user", "")),
            to_user=str(d.get("to_user", "")),
            content=str(d.get("content", "")),
            timestamp=str(d.get("timestamp", "")),
        )

    @classmethod
    def from_json(cls, raw: bytes | str) -> "ChatMessage":
        d = json.loads(raw)
        if not isinstance(d, dict):
            raise ValueError("chat message must be a JSON object")
        return cls.from_dict(d)
