"""Chat web UI with the AI reply co-pilot.

Reference: web/streamlit_app.py — a Streamlit page per user that (a) sends
messages through its node's ``POST /send``, (b) polls ``GET /inbox`` every
2 s, (c) renders messenger-style bubbles sorted by timestamp, and (d) runs
the co-pilot loop: per incoming message, a "Suggest a reply" button calls
the LLM with a fixed template and an accept button posts the suggestion
back through /send (streamlit_app.py:161-190).

Streamlit is not in this image, so the equivalent here is self-contained:
a single-page HTML/JS app served by this tiny process. Behavior parity:

- config via the same env vars: ``NODE_HTTP``, ``OLLAMA_URL``, ``LLM_MODEL``
  (streamlit_app.py:26-28) + additive ``UI_ADDR``.
- 2 s inbox poll with ``after=""`` — full-history refetch, the quirk that
  makes history survive page reloads (SURVEY.md §2).
- sent messages live only in browser memory (the reference keeps them only
  in st.session_state — no persistence, streamlit_app.py:34-37).
- the LLM prompt template is byte-identical to streamlit_app.py:93, the
  60 s timeout matches :95, and failures degrade to the same placeholder
  strings "(LLM error)" / "(LLM unavailable: ...)" (:99-101).

The UI server proxies ``/node/*`` to the node and ``/api/suggest`` to the
LLM so the browser needs no CORS setup.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from importlib import resources
from typing import Optional

from .utils.env import env_float, env_int, env_or
from .utils.http import HttpServer, Request, Response, Router, http_json
from .utils.log import get_logger

log = get_logger("ui")

# Byte-identical to web/streamlit_app.py:93 — part of the observable LLM
# contract the new serving stack must reproduce.
SUGGEST_TEMPLATE = (
    "You are a helpful assistant. Draft a concise, friendly reply to the "
    "following message:\n\n{msg}\n\nReply:"
)
LLM_TIMEOUT_S = 60.0   # streamlit_app.py:95 (reference default;
#                        UI_LLM_TIMEOUT_S overrides per deployment)


class ChatUI:
    def __init__(self, node_http: Optional[str] = None,
                 ollama_url: Optional[str] = None,
                 llm_model: Optional[str] = None,
                 addr: Optional[str] = None) -> None:
        self.node_http = (node_http if node_http is not None
                          else env_or("NODE_HTTP", "http://127.0.0.1:8081")).rstrip("/")
        self.ollama_url = (ollama_url if ollama_url is not None
                           else env_or("OLLAMA_URL", "http://127.0.0.1:11434")).rstrip("/")
        self.llm_model = llm_model if llm_model is not None else env_or("LLM_MODEL", "llama3.1")
        self.addr_cfg = addr if addr is not None else env_or("UI_ADDR", "127.0.0.1:8501")
        # Suggestion length bound. The reference sends NO num_predict
        # (server default applies) and 0 preserves that; operators and
        # the loadgen CPU profile cap it — an unbounded co-pilot reply
        # is the single biggest per-request cost on small hosts.
        self.suggest_predict = env_int("UI_SUGGEST_PREDICT", 0)
        # Upstream LLM deadline. 60 s is the reference's (streamlit_app
        # :95); slow dev-profile hosts raise it so a suggestion that is
        # slow-but-within-SLO completes instead of becoming an error.
        self.llm_timeout_s = env_float("UI_LLM_TIMEOUT_S", LLM_TIMEOUT_S)
        self.router = Router()
        self.router.add("GET", "/", self._index)
        self.router.add("GET", "/config.json", lambda r: Response(200, {
            "node_http": self.node_http, "llm_model": self.llm_model}))
        self.router.add("POST", "/api/suggest", self._suggest)
        self.router.add("POST", "/api/suggest/stream", self._suggest_stream)
        self.router.add("GET", "/node/me", self._proxy_node_get("/me"))
        self.router.add("GET", "/node/inbox", self._proxy_node_get("/inbox"))
        self.router.add("POST", "/node/send", self._proxy_node_post("/send"))
        self.router.add("GET", "/healthz", lambda r: Response(200, {"status": "ok"}))
        self._server: Optional[HttpServer] = None

    # -- handlers ------------------------------------------------------------

    def _index(self, req: Request) -> Response:
        html = (resources.files("p2p_llm_chat_tpu") / "web_static" / "index.html").read_text()
        return Response(200, html, content_type="text/html; charset=utf-8")

    @staticmethod
    def _fwd_headers(req: Request) -> dict:
        """Wire context to carry across a proxy hop: a browser that
        arrived with X-Graft-Trace / X-Session-Id keeps them on the
        upstream leg (the UI never mints either — an untraced browser
        stays untraced)."""
        out = {}
        tid = req.headers.get("x-graft-trace")
        if tid:
            out["X-Graft-Trace"] = tid
        sid = req.headers.get("x-session-id")
        if sid:
            out["X-Session-Id"] = sid
        return out

    def _suggest(self, req: Request) -> Response:
        """ai_suggest (streamlit_app.py:89-101): call the LLM with the fixed
        template; degrade to placeholder strings on any failure."""
        try:
            body = req.json() or {}
        except ValueError:
            return Response(400, {"error": "invalid json"})
        content = str(body.get("content") or "")
        payload = {
            "model": self.llm_model,
            "prompt": SUGGEST_TEMPLATE.format(msg=content),
            "stream": False,
        }
        if self.suggest_predict > 0:
            payload["options"] = {"num_predict": self.suggest_predict}
        try:
            status, resp = http_json(
                "POST", f"{self.ollama_url}/api/generate", payload,
                timeout=self.llm_timeout_s, raise_for_status=False,
                headers=self._fwd_headers(req))
            if status == 200 and isinstance(resp, dict) and "response" in resp:
                suggestion = str(resp["response"]).strip()   # :97-98
            else:
                suggestion = "(LLM error)"                   # :99
        except Exception as e:  # noqa: BLE001
            suggestion = f"(LLM unavailable: {e})"           # :100-101
        return Response(200, {"suggestion": suggestion})

    def _suggest_stream(self, req: Request) -> Response:
        """Streaming co-pilot suggestions: the serve stack already
        streams NDJSON (serve/api.py); this forwards its deltas to the
        browser as ``{"delta", "done"}`` lines so suggestion text appears
        incrementally instead of after the full generation. The
        non-streaming ``/api/suggest`` keeps the reference's buffered
        contract (streamlit_app.py:89-101) for stream:false clients."""
        import urllib.error
        import urllib.request

        try:
            body = req.json() or {}
        except ValueError:
            return Response(400, {"error": "invalid json"})
        content = str(body.get("content") or "")

        # Open the upstream BEFORE committing to a 200 NDJSON stream —
        # the serve front's own discipline ("never a mid-NDJSON error
        # record after a 200 already went out"). In particular a shed
        # (503 + Retry-After, the overload contract) forwards verbatim
        # with its Retry-After, so the browser/loadgen sees well-formed
        # backpressure instead of a buried mid-stream error line.
        payload = {
            "model": self.llm_model,
            "prompt": SUGGEST_TEMPLATE.format(msg=content),
            "stream": True,
        }
        if self.suggest_predict > 0:
            payload["options"] = {"num_predict": self.suggest_predict}
        data = json.dumps(payload).encode("utf-8")
        headers = {"Content-Type": "application/json"}
        # grafttrace (obs/trace.py): a co-pilot request that arrives
        # with an X-Graft-Trace header keeps its id on the serve leg,
        # so the merged timeline covers browser -> UI -> serve. The UI
        # never mints — an untraced browser stays untraced, and the
        # serve front mints its own for ingress accounting.
        tid = req.headers.get("x-graft-trace")
        if tid:
            headers["X-Graft-Trace"] = tid
        # Session affinity rides the hop too: the serve front's router
        # pins X-Session-Id requests to the replica holding their KV.
        sid = req.headers.get("x-session-id")
        if sid:
            headers["X-Session-Id"] = sid
        r = urllib.request.Request(
            f"{self.ollama_url}/api/generate", data=data,
            headers=headers,
            method="POST")
        try:
            resp = urllib.request.urlopen(r, timeout=self.llm_timeout_s)
        except urllib.error.HTTPError as e:
            detail = e.read()[:300].decode("utf-8", "replace")
            headers = {}
            retry = e.headers.get("Retry-After")
            if retry:
                headers["Retry-After"] = retry
            e.close()
            return Response(e.code, {"error": detail or "LLM error"},
                            headers=headers)
        except Exception as e:  # noqa: BLE001 — same degradation
            # strings as the buffered path (streamlit_app.py:100-101);
            # error:true lets the browser treat the text as a failure
            # marker instead of appending it to a partial suggestion.
            # graftcheck: stream-ok single constant yield, no upstream or gauge held
            def unavailable(msg=str(e)):
                yield (json.dumps({
                    "delta": f"(LLM unavailable: {msg})", "done": True,
                    "error": True,
                }) + "\n").encode("utf-8")
            return Response(200, stream=unavailable(),
                            content_type="application/x-ndjson")

        def gen():
            # The finally (not the `with` alone) is what settles things
            # on CLIENT disconnect: HttpServer close()es this generator,
            # GeneratorExit lands at the current yield — which sits
            # OUTSIDE the `with resp:` on the error path — and the
            # upstream serve connection (still holding a decode slot)
            # must be released now, not at GC.
            try:
                with resp:
                    for line in resp:
                        try:
                            obj = json.loads(line)
                        except ValueError:
                            continue
                        done = bool(obj.get("done"))
                        yield (json.dumps({
                            "delta": str(obj.get("response", "")),
                            "done": done,
                        }) + "\n").encode("utf-8")
                        if done:
                            return
                yield (json.dumps({"delta": "", "done": True})
                       + "\n").encode("utf-8")
            except Exception as e:  # noqa: BLE001 — mid-stream failure
                # after deltas already went out: the error record keeps
                # the browser from treating a half suggestion as whole.
                yield (json.dumps({
                    "delta": f"(LLM unavailable: {e})", "done": True,
                    "error": True,
                }) + "\n").encode("utf-8")
            finally:
                try:
                    resp.close()
                except Exception:   # noqa: BLE001 — teardown only
                    pass

        return Response(200, stream=gen(),
                        content_type="application/x-ndjson")

    def _proxy_node_get(self, path: str):
        def handler(req: Request) -> Response:
            q = f"?{urllib.parse.urlencode(req.query)}" if req.query else ""
            try:
                status, body = http_json("GET", f"{self.node_http}{path}{q}",
                                         timeout=5.0, raise_for_status=False,
                                         headers=self._fwd_headers(req))
            except ConnectionError as e:
                return Response(502, {"error": str(e)})
            return Response(status, body)
        return handler

    def _proxy_node_post(self, path: str):
        def handler(req: Request) -> Response:
            try:
                payload = req.json()
            except ValueError:
                return Response(400, {"error": "invalid json"})
            try:
                status, body = http_json("POST", f"{self.node_http}{path}", payload,
                                         timeout=10.0, raise_for_status=False,
                                         headers=self._fwd_headers(req))
            except ConnectionError as e:
                return Response(502, {"error": str(e)})
            return Response(status, body)
        return handler

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ChatUI":
        self._server = HttpServer(self.router, self.addr_cfg).start()
        log.info("chat UI on http://%s (node=%s, llm=%s)",
                 self._server.addr, self.node_http, self.ollama_url)
        return self

    @property
    def url(self) -> str:
        assert self._server is not None
        return self._server.url

    def serve_forever(self) -> None:
        self.start()
        threading.Event().wait()

    def stop(self) -> None:
        if self._server:
            self._server.stop()


def main() -> None:
    ChatUI().serve_forever()


if __name__ == "__main__":
    main()
