"""Tokenizers for the serving stack.

The reference has no tokenizer at all — tokenization happens inside the
out-of-tree Ollama server (SURVEY.md §5 long-context note). In-tree we
provide:

- :class:`BPETokenizer` — a from-scratch byte-level BPE implementation that
  reads HuggingFace ``tokenizer.json`` files (the format llama3/Mixtral
  checkpoints ship with): vocab + ranked merges, GPT-2 byte<->unicode
  mapping, regex pre-tokenization, added special tokens.
- :class:`ByteTokenizer` — a dependency-free fallback (UTF-8 bytes +
  specials) used by tests, FakeLLM-adjacent flows, and synthetic benches so
  the entire stack runs with no tokenizer artifacts on disk.

``load_tokenizer`` picks BPE when a checkpoint directory has tokenizer
files, else bytes.
"""

from __future__ import annotations

import json
import os
import re
from functools import lru_cache
from typing import Optional, Protocol, runtime_checkable


@runtime_checkable
class Tokenizer(Protocol):
    vocab_size: int
    bos_id: int
    eos_id: int

    def encode(self, text: str, add_bos: bool = False) -> list[int]: ...
    def decode(self, ids: list[int]) -> str: ...


# ---------------------------------------------------------------------------
# Byte fallback
# ---------------------------------------------------------------------------

class ByteTokenizer:
    """UTF-8 bytes as ids 0..255; bos=256, eos=257, pad=258."""

    def __init__(self, vocab_size: int = 512) -> None:
        if vocab_size < 259:
            raise ValueError("byte tokenizer needs vocab_size >= 259")
        self.vocab_size = vocab_size
        self.bos_id = 256
        self.eos_id = 257
        self.pad_id = 258

    def encode(self, text: str, add_bos: bool = False) -> list[int]:
        ids = list(text.encode("utf-8"))
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: list[int]) -> str:
        return bytes(i for i in ids if 0 <= i < 256).decode("utf-8", "replace")


# ---------------------------------------------------------------------------
# Byte-level BPE (HF tokenizer.json)
# ---------------------------------------------------------------------------

@lru_cache(maxsize=1)
def _byte_to_unicode() -> dict[int, str]:
    """GPT-2's reversible byte<->printable-unicode mapping."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(ord("¡"), ord("¬") + 1))
          + list(range(ord("®"), ord("ÿ") + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, map(chr, cs)))


def _nl_no_class() -> str:
    """Character-class body for unicode categories Nl+No (letter-like and
    other numbers: ², ½, Ⅻ, ①, …). Python's \\d covers only Nd, but
    llama3's \\p{N} covers Nd∪Nl∪No — these must be in the number branch
    and out of the letters branch or token ids diverge on such inputs.
    Generated from the runtime's own unicodedata tables (~0.2 s, once)."""
    import sys
    import unicodedata
    pts = [cp for cp in range(sys.maxunicode + 1)
           if unicodedata.category(chr(cp)) in ("Nl", "No")]
    ranges = []
    start = prev = pts[0]
    for cp in pts[1:]:
        if cp == prev + 1:
            prev = cp
            continue
        ranges.append((start, prev))
        start = prev = cp
    ranges.append((start, prev))
    esc = lambda c: re.escape(chr(c))  # noqa: E731
    return "".join(esc(a) + ("-" + esc(b) if b > a else "")
                   for a, b in ranges)


_NL_NO = _nl_no_class()

# llama3's pre-tokenization regex (tiktoken cl100k-style), expressed for
# Python's `re` (no possessive quantifiers / \p{..} classes; (?i:...) works).
# The original's unicode classes map as: \p{L} (letters) -> [^\W\d_] minus
# Nl/No (word chars minus all numbers minus underscore); \p{N} (numbers)
# -> [\d + Nl/No]; "not letter, not number" -> [\W_] (digits and Nl/No are
# word chars, so \W already excludes them; underscore added back).
# Keeping numbers out of the word branch is what makes the number branch
# reachable, so digit runs split into <=3-digit groups exactly like the HF
# llama3 tokenizer. Parity with the real \p{..} engine is pinned by
# tests/test_tokenizer.py::test_pretokenizer_matches_llama3_regex_oracle.
_PRETOKEN_RE = re.compile(
    r"(?i:'s|'t|'re|'ve|'m|'ll|'d)"
    rf"|(?:(?![\r\n])[\W_])?[^\W\d_{_NL_NO}]+"   # [^\r\n\p{{L}}\p{{N}}]?\p{{L}}+
    rf"|[\d{_NL_NO}]{{1,3}}"                      # \p{{N}}{{1,3}}
    r"| ?(?:_|[^\s\w])+[\r\n]*"                   # ' ?[^\s\p{L}\p{N}]+[\r\n]*'
    r"|\s*[\r\n]+"
    r"|\s+(?!\S)"
    r"|\s+"
)


class BPETokenizer:
    def __init__(self, vocab: dict[str, int], merges: list[tuple[str, str]],
                 special_tokens: Optional[dict[str, int]] = None,
                 bos_token: str = "<|begin_of_text|>",
                 eos_tokens: tuple[str, ...] = ("<|end_of_text|>", "<|eot_id|>")):
        self._vocab = vocab
        self._inv_vocab = {v: k for k, v in vocab.items()}
        self._ranks = {pair: i for i, pair in enumerate(merges)}
        self._special = dict(special_tokens or {})
        self._inv_special = {v: k for k, v in self._special.items()}
        self._b2u = _byte_to_unicode()
        self._u2b = {v: k for k, v in self._b2u.items()}
        self.vocab_size = max(
            [max(vocab.values(), default=-1)] + list(self._special.values())) + 1
        self.bos_id = self._special.get(bos_token, 0)
        self.eos_id = next((self._special[t] for t in eos_tokens
                            if t in self._special), 0)
        if self._special:
            self._special_re = re.compile(
                "(" + "|".join(re.escape(t) for t in
                               sorted(self._special, key=len, reverse=True)) + ")")
        else:
            self._special_re = None
        self._native = self._init_native()

    def has_special(self, token: str) -> bool:
        """Whether ``token`` is a registered special (serve/engine.py
        keys the llama3 chat template on the header/eot specials)."""
        return token in self._special

    def strip_specials(self, text: str) -> str:
        """Remove every registered special-token string from ``text``.

        ``encode`` maps special strings ANYWHERE in input to their
        control ids — correct for templates the server renders, but a
        forgery vector for untrusted content (a chat message containing
        ``<|eot_id|><|start_header_id|>system...`` would fabricate a
        system turn). Template renderers call this on user-supplied
        parts before interpolation (serve/engine.py render_chat)."""
        if self._special_re is None:
            return text
        return self._special_re.sub("", text)

    def _init_native(self):
        """Bind the C++ merge core (native/bpe_core.cc) when buildable.

        BPE is re-keyed into vocab-id space once here — pair
        (left_id, right_id) -> (rank, merged_id) — so the per-call ctypes
        boundary is plain int32 arrays and the C++ loop never sees
        strings. Returns (lib, handle) or None (pure-Python fallback,
        identical output — pinned by tests/test_tokenizer.py)."""
        import ctypes

        from .utils import native

        lib = native.load("bpe_core")
        if lib is None:
            return None
        keys, vals = [], []
        for (l, r), rank in self._ranks.items():
            li, ri = self._vocab.get(l), self._vocab.get(r)
            mi = self._vocab.get(l + r)
            if li is None or ri is None or mi is None:
                # A merge the id-keyed table can't represent: the Python
                # path would still apply it (then decompose the unknown
                # fragment), so a lossy table would diverge from the
                # pure-Python oracle. Bail to the fallback instead.
                return None
            keys.append((li << 32) | ri)
            vals.append((rank << 32) | mi)
        lib.bpe_new.restype = ctypes.c_void_p
        lib.bpe_new.argtypes = [ctypes.POINTER(ctypes.c_uint64),
                                ctypes.POINTER(ctypes.c_uint64),
                                ctypes.c_int64]
        # Without argtypes ctypes passes the handle as a 32-bit int —
        # pointer truncation, segfault in the finalizer.
        lib.bpe_free.argtypes = [ctypes.c_void_p]
        lib.bpe_apply.restype = ctypes.c_int32
        lib.bpe_apply.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_int32),
                                  ctypes.c_int32,
                                  ctypes.POINTER(ctypes.c_int32)]
        lib.bpe_apply_batch.restype = ctypes.c_int64
        lib.bpe_apply_batch.argtypes = [ctypes.c_void_p,
                                        ctypes.POINTER(ctypes.c_int32),
                                        ctypes.POINTER(ctypes.c_int32),
                                        ctypes.c_int32,
                                        ctypes.POINTER(ctypes.c_int32)]
        # Direct byte -> initial-symbol-id table: the native path skips
        # the byte->unicode string mapping entirely. Only usable when the
        # vocab covers all 256 byte symbols (true for llama3/Mixtral).
        byte_id = [self._vocab.get(self._b2u[b]) for b in range(256)]
        if any(i is None for i in byte_id):
            return None
        self._byte_id = byte_id
        n = len(keys)
        handle = lib.bpe_new((ctypes.c_uint64 * n)(*keys),
                             (ctypes.c_uint64 * n)(*vals), n)
        if not handle:
            return None
        import weakref
        weakref.finalize(self, lib.bpe_free, handle)
        return (lib, handle, ctypes)

    def _encode_chunk_native(self, chunk: str) -> list[int]:
        """Pre-tokenize + merge one chunk through the C++ core in a single
        FFI call (ids concatenated, one length per piece)."""
        lib, handle, ctypes = self._native
        byte_id = self._byte_id
        flat: list[int] = []
        lens: list[int] = []
        for piece in _PRETOKEN_RE.findall(chunk):
            bs = piece.encode("utf-8")
            flat.extend(byte_id[b] for b in bs)
            lens.append(len(bs))
        if not flat:
            return []
        n = len(flat)
        out = (ctypes.c_int32 * n)()
        m = lib.bpe_apply_batch(handle, (ctypes.c_int32 * n)(*flat),
                                (ctypes.c_int32 * len(lens))(*lens),
                                len(lens), out)
        return list(out[:m])

    # -- loading -------------------------------------------------------------

    @classmethod
    def from_file(cls, path: str) -> "BPETokenizer":
        with open(path, encoding="utf-8") as f:
            tj = json.load(f)
        model = tj["model"]
        vocab = model["vocab"]
        merges_raw = model["merges"]
        merges = [tuple(m.split(" ", 1)) if isinstance(m, str) else tuple(m)
                  for m in merges_raw]
        specials = {t["content"]: t["id"] for t in tj.get("added_tokens", [])}
        return cls(vocab, merges, specials)

    # -- bpe core ------------------------------------------------------------

    def _bpe(self, token: str) -> list[int]:
        if self._native is not None and len(token) > 1:
            ids = [self._vocab.get(ch) for ch in token]
            if None not in ids:       # unknown chars: rare; python fallback
                lib, handle, ctypes = self._native
                n = len(ids)
                buf = (ctypes.c_int32 * n)(*ids)
                out = (ctypes.c_int32 * n)()
                m = lib.bpe_apply(handle, buf, n, out)
                return list(out[:m])
        return self._bpe_py(token)

    def _bpe_py(self, token: str) -> list[int]:
        parts = list(token)
        if len(parts) == 1:
            return [self._vocab[token]] if token in self._vocab else []
        while len(parts) > 1:
            best_rank, best_i = None, -1
            for i in range(len(parts) - 1):
                r = self._ranks.get((parts[i], parts[i + 1]))
                if r is not None and (best_rank is None or r < best_rank):
                    best_rank, best_i = r, i
            if best_rank is None:
                break
            parts[best_i:best_i + 2] = [parts[best_i] + parts[best_i + 1]]
        out = []
        for p in parts:
            if p in self._vocab:
                out.append(self._vocab[p])
            else:
                # Unknown fragment: fall back to per-character lookup.
                out.extend(self._vocab[c] for c in p if c in self._vocab)
        return out

    def encode(self, text: str, add_bos: bool = False) -> list[int]:
        ids: list[int] = [self.bos_id] if add_bos else []
        chunks = (self._special_re.split(text) if self._special_re else [text])
        for chunk in chunks:
            if not chunk:
                continue
            if chunk in self._special:
                ids.append(self._special[chunk])
                continue
            if self._native is not None:
                ids.extend(self._encode_chunk_native(chunk))
                continue
            for piece in _PRETOKEN_RE.findall(chunk):
                mapped = "".join(self._b2u[b] for b in piece.encode("utf-8"))
                ids.extend(self._bpe(mapped))
        return ids

    def decode(self, ids: list[int]) -> str:
        out_bytes = bytearray()
        for i in ids:
            if i in self._inv_special:
                out_bytes += self._inv_special[i].encode("utf-8")
                continue
            tok = self._inv_vocab.get(i)
            if tok is None:
                continue
            for ch in tok:
                b = self._u2b.get(ch)
                if b is not None:
                    out_bytes.append(b)
                else:
                    out_bytes += ch.encode("utf-8")
        return out_bytes.decode("utf-8", "replace")


# ---------------------------------------------------------------------------

def load_tokenizer(ckpt_dir: Optional[str], vocab_size: int = 512) -> Tokenizer:
    """BPE from <ckpt_dir>/tokenizer.json when present; byte fallback
    otherwise (the no-artifacts path tests and synthetic benches use)."""
    if ckpt_dir:
        tj = os.path.join(ckpt_dir, "tokenizer.json")
        if os.path.exists(tj):
            return BPETokenizer.from_file(tj)
    return ByteTokenizer(vocab_size=vocab_size)
