"""Pipeline parallelism over the ``pp`` mesh axis (GPipe-style).

The layer stack is cut into ``pp`` contiguous stages — each device holds
``L/pp`` layers' weights and KV — and activations hop stage-to-stage with
``jax.lax.ppermute`` while microbatches stream through, so at steady state
every stage computes a different microbatch concurrently. This completes
the parallelism matrix next to dp/tp (parallel/sharding.py), ep
(models/mixtral.py), and sp (parallel/ring.py); the reference has no
distributed machinery at all (SURVEY.md §2: everything delegated to
Ollama).

TPU-first shape:
- One ``shard_map`` program; the schedule is a statically unrolled loop of
  ``M + pp - 1`` ticks (M = microbatches), so XLA sees straight-line code
  and overlaps each tick's ppermute with the next tick's matmuls.
- Stage-local layers run under one ``lax.scan`` (same constant-graph
  trick as models/llama.py); stage weights are the stacked ``[L, ...]``
  leaves sharded over ``pp`` on the layer axis — no per-stage pytrees.
- No traced control flow: ``axis_index("pp")`` is traced, so stages never
  branch on "is it my turn". Every stage computes every tick; a stage's
  output is *correct* exactly on the tick its input arrived (the bubble
  ticks produce garbage that flows nowhere: KV/logit writes ride
  out-of-range scatter indices with ``mode="drop"``).
- Embedding/final-norm/lm_head are replicated; stage 0 embeds, the last
  stage projects. KV cache stays ``[L, B, S, Hkv, D]`` with the layer
  axis sharded over ``pp`` — each stage owns its layers' pages.

Decode (:func:`pp_decode_step`) flows the one-token batch through the
stages in ``pp`` ticks (inference pipelining; the classic decode bubble).
It exists for contract completeness and multi-chip validation — serving
configs on one slice prefer tp/sp, which decode in one tick.

Parity with models/llama.py prefill/decode_step is pinned by
tests/test_pipeline.py on the virtual CPU mesh and by
``__graft_entry__.dryrun_multichip``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from ..models.configs import ModelConfig
from ..models.layers import (attend_gqa, causal_mask, length_mask, rms_norm,
                             rope_frequencies)
from ..models.llama import KVCache, _attn_qkv, _post_attn
from ..models.quant import mm


def _stage_specs(params: dict) -> dict:
    """in_specs pytree: stacked layer leaves sharded over pp on the layer
    axis, everything else replicated. Descends into QTensor leaves too
    (both q and s carry the leading [L] axis)."""
    def walk(d: dict, in_layers: bool) -> dict:
        out = {}
        for k, v in d.items():
            if isinstance(v, dict):
                out[k] = walk(v, in_layers or k == "layers")
            else:
                out[k] = jax.tree.map(
                    lambda _: P("pp") if (in_layers or k == "layers")
                    else P(), v)
        return out
    return walk(params, False)


def pp_prefill(params: dict, config: ModelConfig, tokens: jax.Array,
               prompt_lens: jax.Array, mesh: Mesh,
               microbatches: Optional[int] = None,
               mlp_fn=None) -> tuple[jax.Array, KVCache]:
    """Pipeline-parallel prefill: llama.prefill's contract with the layer
    stack sharded into ``pp`` stages and the batch streamed through as
    microbatches.

    tokens: [B,S] right-padded (B divisible by ``microbatches``, default
    pp); prompt_lens: [B]. Returns (logits [B,S,vocab] f32, KVCache whose
    k/v layer axis is pp-sharded, max_seq = S).
    """
    pp = mesh.shape["pp"]
    assert mesh.size == pp, (
        f"pipeline path runs over pp only (mesh {dict(mesh.shape)}); "
        "set other axes to 1")
    L = config.num_layers
    assert L % pp == 0, f"{L} layers not divisible by pp={pp}"
    B, S = tokens.shape
    M = microbatches or min(pp, B)
    assert B % M == 0, f"batch {B} not divisible by microbatches {M}"
    per = B // M
    Lp = L // pp
    inv_freq = rope_frequencies(config)
    H = config.hidden_size
    mask = causal_mask(S, S, 0)

    def device_fn(params, tokens):
        my = jax.lax.axis_index("pp")
        lp_local = params["layers"]            # [Lp, ...] leaves
        dtype = params["embed"].dtype
        ck = jnp.zeros((Lp, B, S, config.num_kv_heads, config.head_dim),
                       dtype)
        cv = jnp.zeros_like(ck)
        logits = jnp.zeros((B, S, config.vocab_size), jnp.float32)
        h = jnp.zeros((per, S, H), dtype)
        positions = jnp.broadcast_to(jnp.arange(S)[None, :], (per, S))
        fwd = [(i, i + 1) for i in range(pp - 1)]

        for t in range(M + pp - 1):            # static pipeline schedule
            # Stage 0 injects microbatch t (clamped; extra ticks recompute
            # the last microbatch — their writes drop via the sentinel).
            mb = min(t, M - 1)
            inject = params["embed"][
                jax.lax.dynamic_slice_in_dim(tokens, mb * per, per, 0)]
            h = jnp.where(my == 0, inject, h)
            # This tick, stage `my` holds microbatch m = t - my; valid
            # only in [0, M). Invalid ticks aim their writes out of range.
            m = t - my
            valid = (m >= 0) & (m < M)
            rows = jnp.where(valid, m * per + jnp.arange(per), B)

            def body(carry, xs):
                h, ck, cv = carry
                lp, layer = xs
                q, k, v = _attn_qkv(h, lp, config, inv_freq, positions,
                                    None, {})
                ck = ck.at[layer, rows[:, None],
                           positions].set(k, mode="drop")
                cv = cv.at[layer, rows[:, None],
                           positions].set(v, mode="drop")
                attn = attend_gqa(q, k, v, mask)
                h = _post_attn(h, attn, lp, config, None, {}, mlp_fn)
                return (h, ck, cv), None

            (h, ck, cv), _ = jax.lax.scan(body, (h, ck, cv),
                                          (lp_local, jnp.arange(Lp)))
            # Last stage projects its finished microbatch into the logits
            # buffer (drop-masked like the cache writes).
            hf = rms_norm(h, params["final_norm"], config.rms_norm_eps)
            lm_head = (params["embed"].T if config.tie_embeddings
                       else params["lm_head"])
            lg = mm(hf, lm_head).astype(jnp.float32)
            out_rows = jnp.where(valid & (my == pp - 1),
                                 m * per + jnp.arange(per), B)
            logits = logits.at[out_rows].set(lg, mode="drop")
            if fwd:
                h = jax.lax.ppermute(h, "pp", fwd)

        # Only the last stage filled `logits`; sum-across-stages recovers
        # it (all other stages contributed zeros).
        return jax.lax.psum(logits, "pp"), ck, cv

    mapped = shard_map(
        device_fn, mesh=mesh,
        in_specs=(_stage_specs(params), P()),
        out_specs=(P(), P("pp"), P("pp")),
        check_rep=False,
    )
    logits, ck, cv = mapped(params, tokens)
    return logits, KVCache(k=ck, v=cv, lengths=prompt_lens.astype(jnp.int32))


def pp_decode_step(params: dict, config: ModelConfig, tokens: jax.Array,
                   cache: KVCache, mesh: Mesh,
                   active: Optional[jax.Array] = None,
                   mlp_fn=None) -> tuple[jax.Array, KVCache]:
    """One decode step against a pp-sharded cache (layer axis over pp).

    Same contract as models/llama.decode_step, including the parked-row
    ``active`` semantics (writes at an unadvanced length are overwritten
    before anything trusts them). The token batch crosses the ``pp``
    stages in pp ticks. tokens: [B,1]. Returns (logits [B,1,vocab]
    replicated, advanced cache)."""
    pp = mesh.shape["pp"]
    assert mesh.size == pp, "pp-only path; see pp_prefill"
    B = tokens.shape[0]
    max_seq = cache.k.shape[2]
    inv_freq = rope_frequencies(config)
    H = config.hidden_size
    Lp = config.num_layers // pp

    def device_fn(params, tokens, ck, cv, lengths):
        my = jax.lax.axis_index("pp")
        positions = lengths[:, None]                      # [B,1]
        mask = length_mask(max_seq, lengths + 1)
        rows_all = jnp.arange(B)
        logits = jnp.zeros((B, 1, config.vocab_size), jnp.float32)
        h = jnp.zeros((B, 1, H), params["embed"].dtype)
        fwd = [(i, i + 1) for i in range(pp - 1)]

        for t in range(pp):
            h = jnp.where(my == 0, params["embed"][tokens], h)
            # Stage `my` holds the real activation exactly at tick t == my;
            # other ticks' writes aim out of range and drop.
            ok = t == my
            rows = jnp.where(ok, rows_all, B)

            def body(carry, xs):
                h, ck, cv = carry
                lp, layer = xs
                q, k, v = _attn_qkv(h, lp, config, inv_freq, positions,
                                    None, {})
                ck = ck.at[layer, rows[:, None],
                           positions].set(k, mode="drop")
                cv = cv.at[layer, rows[:, None],
                           positions].set(v, mode="drop")
                k_layer = jax.lax.dynamic_index_in_dim(ck, layer, 0,
                                                       keepdims=False)
                v_layer = jax.lax.dynamic_index_in_dim(cv, layer, 0,
                                                       keepdims=False)
                attn = attend_gqa(q, k_layer, v_layer, mask)
                h = _post_attn(h, attn, lp, config, None, {}, mlp_fn)
                return (h, ck, cv), None

            (h, ck, cv), _ = jax.lax.scan(body, (h, ck, cv),
                                          (params["layers"], jnp.arange(Lp)))
            hf = rms_norm(h, params["final_norm"], config.rms_norm_eps)
            lm_head = (params["embed"].T if config.tie_embeddings
                       else params["lm_head"])
            lg = mm(hf, lm_head).astype(jnp.float32)
            out_rows = jnp.where(ok & (my == pp - 1), rows_all, B)
            logits = logits.at[out_rows].set(lg, mode="drop")
            if fwd:
                h = jax.lax.ppermute(h, "pp", fwd)

        return jax.lax.psum(logits, "pp"), ck, cv

    mapped = shard_map(
        device_fn, mesh=mesh,
        in_specs=(_stage_specs(params), P(), P("pp"), P("pp"), P()),
        out_specs=(P(), P("pp"), P("pp")),
        check_rep=False,
    )
    logits, ck, cv = mapped(params, tokens, cache.k, cache.v, cache.lengths)
    inc = (jnp.ones_like(cache.lengths) if active is None
           else active.astype(jnp.int32))
    return logits, KVCache(k=ck, v=cv, lengths=cache.lengths + inc)
