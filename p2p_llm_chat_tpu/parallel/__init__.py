"""Device mesh, sharding rules, and collectives — the TPU-native 'comms
backend' (SURVEY.md §2 parallelism checklist, §5 distributed-communication).

The reference has no distributed ML machinery; its comms are libp2p streams
and HTTP. Here, intra-slice parallelism is expressed the XLA way: a
:class:`jax.sharding.Mesh` over the chips, logical-axis sharding rules
binding parameter/activation axes to mesh axes, and XLA-inserted collectives
(psum / all-gather / reduce-scatter / ppermute) over ICI — no NCCL/MPI
equivalent is written by hand. DCN-scale (multi-host) uses the same
mechanism: JAX global meshes span hosts transparently.

- :mod:`mesh`      — mesh construction (dp/tp/ep/sp axes) and config
- :mod:`sharding`  — logical-axis rules -> PartitionSpecs for params and
                     activations (tensor parallel for dense models, expert
                     parallel for MoE, sequence/context parallel hooks)
- :mod:`ring`      — ring attention over sequence-parallel shards (ppermute
                     over ICI) for long-context
"""

from .mesh import MeshConfig, make_mesh, local_mesh
from .sharding import LogicalRules, DEFAULT_RULES, spec_for, shard_params

__all__ = ["MeshConfig", "make_mesh", "local_mesh", "LogicalRules",
           "DEFAULT_RULES", "spec_for", "shard_params"]
