"""Multi-host (DCN) distributed runtime entry points.

The reference's only "distributed backend" is point-to-point chat streams
(SURVEY.md §5: no NCCL/MPI/Gloo anywhere); the TPU-native equivalent is
XLA collectives — ICI within a slice, DCN between hosts — driven entirely
by device meshes. This module is the multi-host glue:

- :func:`init_distributed` brings a process into the JAX distributed
  runtime (coordinator handshake, global device visibility). After it,
  ``jax.devices()`` spans every host and the regular ``make_mesh`` /
  ``shard_map`` programs run unchanged — XLA routes collectives over ICI
  inside a slice and DCN across slices.
- :func:`multihost_mesh` builds the hybrid mesh for that world: the
  slower DCN axis carries the replication-style parallelism (``dp`` —
  gradient/batch-level, least-frequent comms) while tp/ep/sp stay inside
  a slice on ICI, the layout the bandwidth hierarchy demands.

Env surface (cluster-launcher friendly, same env-first style as the rest
of the framework): ``JAX_COORDINATOR`` (host:port of process 0),
``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``. On Cloud TPU pods
``jax.distributed.initialize()`` auto-discovers all three; the envs are
for bare-metal/manual launches.

Single-host fallback: with no coordinator configured this is a no-op and
everything runs on the local devices — the same code path the tests and
the single-chip bench use.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
from jax.sharding import Mesh

from ..utils.log import get_logger
from .mesh import AXES, MeshConfig, make_mesh

log = get_logger("parallel.distributed")


def _enable_cpu_collectives() -> None:
    """Multi-process on the CPU platform needs a cross-process collectives
    backend. jax's ``jax_cpu_collectives_implementation`` defaults to
    ``"none"`` (and reads no environment variable — it is settable only
    via ``jax.config.update`` before the CPU client exists), under which
    EVERY cross-process computation — including the one-int psum inside
    ``multihost_utils.broadcast_one_to_all`` — dies with "Multiprocess
    computations aren't implemented on the CPU backend". That was the
    root cause of the test_multihost_serve / test_distributed failures
    noted since round 8: the multihost serve front answered 500 at the
    first POST because the leader's command broadcast could never run.
    Flip the flag to gloo here, before ``jax.distributed.initialize``
    touches any backend — and only when this process is explicitly
    pinned to CPU (``jax_platforms``/``JAX_PLATFORMS``); accelerator
    runs keep jax's default. Best-effort on purpose: a jax build
    without the flag (or without gloo compiled in) just keeps its
    default."""
    plats = (getattr(jax.config, "jax_platforms", None)
             or os.environ.get("JAX_PLATFORMS", "") or "")
    if plats.split(",")[0].strip().lower() != "cpu":
        return
    # The flag holder is update()-able but NOT readable as a jax.config
    # attribute (it is a Flag, not a State) — read the current value off
    # the xla_bridge holder so an operator's explicit choice (e.g. mpi
    # via absl flags) is never overridden. The read is best-effort in
    # its OWN try: xla_bridge is private and has churned before; a
    # moved/renamed holder must degrade to "assume unset" and still
    # attempt the update below, not silently disable the whole fix
    # (which would resurrect the exact "Multiprocess computations
    # aren't implemented" failure this function root-caused).
    cur = None
    try:
        from jax._src import xla_bridge as _xb
        cur = _xb.CPU_COLLECTIVES_IMPLEMENTATION.value
    except Exception:   # noqa: BLE001 — private module; treat as unset
        pass
    if cur not in (None, "none"):
        return                      # operator chose an implementation
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        log.info("CPU platform multi-process: enabled gloo collectives")
    except Exception:   # noqa: BLE001 — flag absent on older/newer jax
        log.warning("no gloo CPU collectives in this jax build; "
                    "multi-process CPU computations may be unsupported")


def init_distributed(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> bool:
    """Join the multi-host runtime; returns True when distributed mode is
    active. No-op (False) when neither args nor env configure a
    coordinator and the platform can't auto-discover one."""
    coordinator = coordinator or os.environ.get("JAX_COORDINATOR")
    n = num_processes if num_processes is not None else int(
        os.environ.get("JAX_NUM_PROCESSES", "0") or 0)
    pid = process_id if process_id is not None else int(
        os.environ.get("JAX_PROCESS_ID", "-1"))
    if coordinator is None and n == 0:
        return False
    _enable_cpu_collectives()
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=n or None,
        process_id=pid if pid >= 0 else None,
    )
    log.info("distributed runtime up: process %d/%d, %d global devices",
             jax.process_index(), jax.process_count(), len(jax.devices()))
    return True


def multihost_mesh(cfg: MeshConfig) -> Mesh:
    """Mesh over the global (multi-host) device set with the DCN/ICI
    split: ``dp`` spans hosts over DCN; pp/ep/sp/tp stay slice-local on
    ICI. ``cfg.size`` must equal the global device count and ``cfg.dp``
    must be a multiple of the process count (whole slices per replica).
    """
    n_proc = jax.process_count()
    if n_proc == 1:
        return make_mesh(cfg)
    devices = jax.devices()
    if cfg.size != len(devices):
        raise ValueError(f"mesh size {cfg.size} != global device count "
                         f"{len(devices)}")
    # Key the DCN layout on the SLICE topology, not the process count: a
    # slice can span several hosts (its devices are all on one ICI
    # fabric), so slices — not processes — are the unit a dp replica
    # must not straddle. Genuinely multi-slice pods go through the
    # hybrid builder, and an error from it (or a dp that doesn't divide
    # the slice count) is a real misconfiguration that must surface —
    # silently substituting an ICI-oblivious placement would bury a
    # severe interconnect performance cliff. Everything else — non-TPU
    # platforms, the forced-host test path (every CPU device reports
    # slice 0), a single multi-host slice — has no DCN hop to lay out,
    # and takes the process-grouped reshape.
    slice_ids = {getattr(d, "slice_index", None) for d in devices}
    if None not in slice_ids and len(slice_ids) > 1:
        n_slices = len(slice_ids)
        if cfg.dp % n_slices:
            raise ValueError(
                f"dp={cfg.dp} must be a multiple of slice count "
                f"{n_slices} (DCN carries dp; a replica cannot straddle "
                "a slice boundary)")
        from jax.experimental import mesh_utils
        ici = (cfg.dp // n_slices, cfg.pp, cfg.ep, cfg.sp, cfg.tp)
        dcn = (n_slices, 1, 1, 1, 1)
        arr = mesh_utils.create_hybrid_device_mesh(ici, dcn)
    else:
        # Group by process manually: dp outermost over sorted process
        # blocks — each process's devices fill whole dp rows, so a
        # replica never straddles a host.
        if cfg.dp % n_proc:
            raise ValueError(
                f"dp={cfg.dp} must be a multiple of process count "
                f"{n_proc} (a replica cannot straddle a host boundary)")
        import numpy as np
        log.warning(
            "single-slice or non-TPU device topology (%d slice ids over "
            "%d processes): building a process-grouped mesh instead of "
            "the ICI/DCN hybrid layout",
            len(slice_ids - {None}) or 1, n_proc)
        devs = sorted(devices, key=lambda d: (d.process_index, d.id))
        arr = np.array(devs).reshape(cfg.dp, cfg.pp, cfg.ep, cfg.sp,
                                     cfg.tp)
    return Mesh(arr, AXES)
