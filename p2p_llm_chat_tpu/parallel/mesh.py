"""Device-mesh construction.

Canonical mesh axes for the whole framework (scoped by BASELINE.json's
configs — TP for 70B over ICI, EP for Mixtral, DP/batching, and sequence/
pipeline axes so context and pipeline parallelism can attach, per
SURVEY.md §2):

- ``dp``: data parallel (replicated weights, sharded batch)
- ``pp``: pipeline parallel (layer stack sharded into stages —
  parallel/pipeline.py)
- ``tp``: tensor parallel (sharded heads / mlp / vocab)
- ``ep``: expert parallel (sharded experts; reuses tp chips for dense parts)
- ``sp``: sequence/context parallel (ring attention shards)

A mesh never needs all axes > 1; size-1 axes cost nothing under XLA's
partitioner, so every program is written against the full 5-axis mesh.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("dp", "pp", "ep", "sp", "tp")


@dataclass(frozen=True)
class MeshConfig:
    dp: int = 1
    pp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    @property
    def size(self) -> int:
        return self.dp * self.pp * self.ep * self.sp * self.tp

    @classmethod
    def for_devices(cls, n: int, tp: int | None = None) -> "MeshConfig":
        """Default layout: everything tensor-parallel (the decode-serving
        sweet spot on a single slice — weights sharded, batch replicated)."""
        return cls(tp=n if tp is None else tp,
                   dp=1 if tp is None else n // tp)


def make_mesh(cfg: MeshConfig, devices: list | None = None) -> Mesh:
    """Build a Mesh with the canonical axis order.

    Axis order matters for ICI locality: ``tp`` is innermost so
    tensor-parallel collectives (the per-layer latency-critical ones) ride
    neighbouring chips; ``dp`` is outermost (least-frequent comms).

    When the mesh covers every visible device, device assignment goes
    through ``mesh_utils.create_device_mesh``, which matches the logical
    axes onto the slice's physical ICI topology (ring/torus orderings)
    instead of flat enumeration order — measurably better collective
    bandwidth on real 2D-torus slices, identical behavior on CPU.
    """
    shape = (cfg.dp, cfg.pp, cfg.ep, cfg.sp, cfg.tp)
    devs = devices if devices is not None else jax.devices()
    if cfg.size > len(devs):
        raise ValueError(f"mesh needs {cfg.size} devices, have {len(devs)}")
    if devices is None and cfg.size == len(devs):
        try:
            from jax.experimental import mesh_utils
            return Mesh(mesh_utils.create_device_mesh(shape), AXES)
        except Exception:   # noqa: BLE001 — topology helper is best-effort
            pass
    return Mesh(np.array(devs[: cfg.size]).reshape(shape), AXES)


def local_mesh(tp: int | None = None) -> Mesh:
    """Mesh over all locally visible devices (single-host path)."""
    n = len(jax.devices())
    return make_mesh(MeshConfig.for_devices(n, tp=tp))
