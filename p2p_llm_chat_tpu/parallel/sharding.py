"""Logical-axis sharding rules -> PartitionSpecs.

Model code names parameter/activation dimensions with *logical* axes
("embed", "heads", "mlp", "vocab", "experts", ...); this module binds them
to mesh axes ("dp", "ep", "sp", "tp"). Changing the parallelism layout =
changing the rule table, not the model. This is the standard scalable-JAX
recipe (mesh -> annotate -> let XLA insert collectives) — the TPU-native
replacement for hand-written NCCL calls (SURVEY.md §5).

Tensor-parallel layout for llama-family (Megatron-style, one psum per
block, scoped by BASELINE.json config 4):

- attention: q/k/v projections column-sharded over heads ("heads"/"kv_heads"
  -> tp), output projection row-sharded ("heads" input dim -> tp) => one
  all-reduce after o_proj.
- MLP: gate/up column-sharded ("mlp" -> tp), down row-sharded => one
  all-reduce after down.
- embeddings/lm_head sharded over "vocab" -> tp.
- MoE (config 5): experts sharded over "experts" -> ("ep","tp") so an
  8-expert model on 8 chips keeps exactly one expert's weights per chip.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.log import get_logger

log = get_logger("parallel.sharding")


# logical axis -> mesh axis (or None = replicated). A logical axis may map to
# a tuple of mesh axes (sharded over their product).
LogicalRules = dict[str, Any]

DEFAULT_RULES: LogicalRules = {
    # weights
    "embed": None,            # hidden dim of residual stream — replicated
    "heads": "tp",            # query heads
    "kv_heads": "tp",         # kv heads (GQA)
    "head_dim": None,
    "mlp": "tp",              # ffn intermediate
    "vocab": "tp",            # embedding/lm_head vocab dim
    "experts": ("ep", "tp"),  # MoE expert dim
    "expert_mlp": None,       # per-expert ffn intermediate (already sharded
                              # over experts; keep dense within an expert)
    # activations
    "batch": "dp",
    "seq": "sp",              # sequence/context parallel shards
    "act_heads": "tp",
    "act_embed": None,
    "act_mlp": "tp",
    "act_vocab": "tp",
    "kv_seq": None,           # kv-cache length axis — replicated under TP
}


def spec_for(logical_axes: tuple[Optional[str], ...],
             rules: LogicalRules = DEFAULT_RULES) -> P:
    """PartitionSpec for a tensor whose dims carry these logical names."""
    out = []
    for ax in logical_axes:
        if ax is None:
            out.append(None)
        else:
            if ax not in rules:
                raise KeyError(f"unknown logical axis {ax!r}")
            out.append(rules[ax])
    # Trim trailing Nones (canonical PartitionSpec form).
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def sharding_for(mesh: Mesh, logical_axes: tuple[Optional[str], ...],
                 rules: LogicalRules = DEFAULT_RULES) -> NamedSharding:
    return NamedSharding(mesh, spec_for(logical_axes, rules))


def tree_specs(axes_tree: Any, rules: LogicalRules = DEFAULT_RULES) -> Any:
    """Map a pytree of logical-axis tuples to a pytree of PartitionSpecs."""
    return jax.tree.map(
        lambda axes: spec_for(axes, rules), axes_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x),
    )


def shard_params(params: Any, axes_tree: Any, mesh: Mesh,
                 rules: LogicalRules = DEFAULT_RULES) -> Any:
    """Device-put a param pytree with shardings derived from its axes tree."""
    specs = tree_specs(axes_tree, rules)
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        params, specs,
    )


def constrain(x: jax.Array, mesh: Optional[Mesh],
              logical_axes: tuple[Optional[str], ...],
              rules: LogicalRules = DEFAULT_RULES) -> jax.Array:
    """In-jit activation sharding hint; no-op when mesh is None (single
    device / testing).

    Dims whose size the bound mesh axes don't divide evenly are left
    unconstrained instead of forcing XLA into involuntary full
    rematerialisation (hit by tiny test configs, e.g. 2 kv heads on tp=4;
    production head/mlp/vocab dims always divide)."""
    if mesh is None:
        return x
    spec = list(spec_for(logical_axes, rules))
    spec += [None] * (x.ndim - len(spec))
    for i, entry in enumerate(spec):
        if entry is None or i >= x.ndim:
            # Rank mismatch falls through to with_sharding_constraint,
            # whose error names the spec and the value's rank.
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        n = 1
        for a in axes:
            n *= mesh.shape[a]
        if x.shape[i] % n:
            log.warning("dropping sharding %r on dim %d (size %d %% %d != 0) "
                        "of %s tensor — replicated instead", entry, i,
                        x.shape[i], n, x.shape)
            spec[i] = None
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(*spec)))
