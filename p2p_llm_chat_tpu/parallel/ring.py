"""Ring attention + sequence-parallel serving over the ``sp`` mesh axis.

Long-context support the reference delegates to Ollama wholesale (it never
even sends history — web/streamlit_app.py:93 wraps one message in a fixed
template). TPU-native design instead of a port:

- **Prefill** (:func:`ring_prefill`): the prompt's sequence dim is sharded
  over ``sp`` via ``shard_map``; every device runs the full layer stack on
  its chunk while k/v chunks rotate around the ring with
  ``jax.lax.ppermute`` — classic ring attention (flash/online-softmax
  accumulation in f32, one hop per step, comms overlapped with the chunk
  matmuls by XLA's async collectives). HBM per device holds 1/sp of the
  activations and KV, so max context scales linearly with the ring size.
- **Decode** (:func:`sp_decode_step`): the KV cache stays sequence-sharded
  after prefill. Each device computes partial flash statistics (m, l, acc)
  of the one query token against its local KV shard; the partials merge
  with one ``pmax`` + two ``psum``s (the distributed-softmax reduction —
  an "all-to-all" sequence-parallel decode, comms O(B·Hq·D) per step,
  independent of context length).

Both paths are numerically identical (f32 softmax statistics) to the dense
single-device oracle in models/llama.py — pinned by tests/test_ring.py on
the virtual CPU mesh and the driver's ``dryrun_multichip``.

**TP×SP composition**: a ``tp`` axis alongside ``sp`` shards heads and
the MLP intermediate Megatron-style INSIDE the shard_map body — q/k/v
projections are column-sharded (each tp device runs the ring over its
own kv-head group; ring hops move 1/tp of the kv bytes), and the output/
down projections are row-sharded with one ``psum`` over ``tp`` each.
This is the configuration a 70B-class long-context deployment needs:
the sequence dim scales context over sp while tp keeps the per-device
weight shard small. Params must be sharded with :func:`ring_param_specs`
(embed/lm_head replicated — the vocab-sharded embedding gather is not
worth the masked-gather+psum inside this path).

**SP×EP composition** (long-context Mixtral): an ``ep`` axis alongside
``sp`` shards the expert-stacked FFN weights; each device's sequence
chunk is replicated across its ep group, so routing is computed
identically everywhere, every device dispatches its chunk's tokens into
ONLY its local experts' capacity buckets (:func:`moe_ring_mlp_fn`), and
one ``psum`` over ep combines — tokens never move between devices, only
the O(B·Sl·H) combine does. MoE under ``tp`` inside the ring remains
future work (expert weights already shard over ("ep","tp") in the
non-ring path, parallel/sharding.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from ..models.configs import ModelConfig
from ..models.layers import NEG_INF, apply_rope, rms_norm, rope_frequencies
from ..models.llama import KVCache
from ..models.quant import mm
from .sharding import DEFAULT_RULES, tree_specs

# Logical rules for the ring path: attention/MLP tp-sharded as usual,
# embeddings and lm_head replicated (the device_fn gathers/projects the
# full vocab; h is tp-replicated after each block's psum).
RING_RULES = dict(DEFAULT_RULES, vocab=None, act_vocab=None)


def ring_param_specs(axes_tree) -> object:
    """PartitionSpec tree for ring-path params (models/*.param_axes ->
    specs under RING_RULES). Shard params with these before calling
    ring_prefill/sp_decode_step on a tp>1 mesh; the shard_map in_specs
    use the same tree, so layouts always agree."""
    return tree_specs(axes_tree, RING_RULES)


def _attn_qkv_local(h, lp, config: ModelConfig, inv_freq, positions):
    """Pre-norm + q/k/v projections + rope on LOCAL head shards: under
    tp the weight columns arriving here are this device's head group, so
    head counts come from the projection widths, not config (llama's
    _attn_qkv reshapes with the global config.num_heads)."""
    B, S, _ = h.shape
    D = config.head_dim
    x = rms_norm(h, lp["attn_norm"], config.rms_norm_eps)
    q = mm(x, lp["wq"]).reshape(B, S, -1, D)
    k = mm(x, lp["wk"]).reshape(B, S, -1, D)
    v = mm(x, lp["wv"]).reshape(B, S, -1, D)
    q = apply_rope(q, positions, inv_freq)
    k = apply_rope(k, positions, inv_freq)
    return q, k, v


def _post_attn_tp(h, attn, lp, config: ModelConfig, mlp_fn,
                  tp_axis: Optional[str]):
    """Output projection + residual + MLP + residual with row-sharded
    wo/w_down under tp: one psum after each row-sharded matmul (the
    Megatron pattern, written explicitly because shard_map bodies use
    collectives, not sharding constraints)."""
    B, S = attn.shape[:2]
    attn = attn.reshape(B, S, -1)
    o = mm(attn, lp["wo"])
    if tp_axis is not None:
        o = jax.lax.psum(o, tp_axis)
    h = h + o
    x = rms_norm(h, lp["mlp_norm"], config.rms_norm_eps)
    if mlp_fn is not None:
        mlp = mlp_fn(x, lp, None, {})
    else:
        g = jax.nn.silu(mm(x, lp["w_gate"])) * mm(x, lp["w_up"])
        mlp = mm(g, lp["w_down"])
        if tp_axis is not None:
            mlp = jax.lax.psum(mlp, tp_axis)
    return h + mlp


def moe_ring_mlp_fn(config: ModelConfig, ep_axis: Optional[str]):
    """Sparse-MoE MLP for the ring/sp shard_map body with experts sharded
    over ``ep_axis`` (None = experts replicated, sp-only).

    The device's sequence chunk is replicated across its ep group
    (ring_prefill's in_specs shard tokens over sp only), so every device
    computes identical routing, scatters its chunk's tokens into its
    LOCAL experts' buckets (the same scatter/gather dispatch as
    models/mixtral.moe_mlp, bucketed by local expert id), runs its
    expert shard's FFNs, and the per-token combine psums over ep —
    non-owners contribute exact zeros via the fill-gather. Math matches
    mixtral.moe_mlp exactly (dropless: C = T bounds every expert's
    assignment count).
    """
    from ..models.quant import q_einsum

    k = config.num_experts_per_tok
    ne_total = config.num_experts

    def fn(x, lp, _mesh, _rules):
        B, S, H = x.shape
        T = B * S
        w_gate = lp["w_gate"]                    # [NE_local, H, F] shard
        ne_local = (w_gate.q if hasattr(w_gate, "q") else w_gate).shape[0]
        xt = x.reshape(T, H)
        logits = xt.astype(jnp.float32) @ lp["router"].astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1)                # [T, NE]
        top_w, top_i = jax.lax.top_k(probs, k)
        top_w = top_w / jnp.sum(top_w, axis=-1, keepdims=True)

        # Position-in-expert over GLOBAL expert ids — identical on every
        # ep device, so bucket slots agree without communication.
        sel = jax.nn.one_hot(top_i, ne_total, dtype=jnp.int32)
        flat = sel.reshape(T * k, ne_total)
        pos = jnp.cumsum(flat, axis=0) - flat
        slot = jnp.sum(flat * pos, axis=-1)                    # [T*k]
        expert = top_i.reshape(T * k)
        base = (jax.lax.axis_index(ep_axis) * ne_local
                if ep_axis is not None else 0)
        local_e = expert - base
        owned = (local_e >= 0) & (local_e < ne_local)
        C = T                                    # dropless: slot < T
        idx = jnp.where(owned, local_e * C + slot, ne_local * C)

        x_rep = jnp.repeat(xt, k, axis=0)                      # [T*k, H]
        xin = jnp.zeros((ne_local * C, H), xt.dtype).at[idx].set(
            x_rep, mode="drop").reshape(ne_local, C, H)
        g = jax.nn.silu(q_einsum("ech,ehf->ecf", xin, lp["w_gate"]))
        u = q_einsum("ech,ehf->ecf", xin, lp["w_up"])
        y = q_einsum("ecf,efh->ech", g * u, lp["w_down"])      # [NEl,C,H]
        gathered = jnp.take(y.reshape(ne_local * C, H), idx, axis=0,
                            mode="fill", fill_value=0)         # [T*k, H]
        out = jnp.sum(gathered.reshape(T, k, H).astype(jnp.float32)
                      * top_w[..., None], axis=1)
        if ep_axis is not None:
            out = jax.lax.psum(out, ep_axis)
        return out.astype(x.dtype).reshape(B, S, H)

    return fn


def _chunk_scores(q: jax.Array, k: jax.Array) -> jax.Array:
    """GQA scores of a q chunk against a kv chunk, f32 on the MXU.

    q: [B,Sq,Hq,D]; k: [B,Sk,Hkv,D]. Returns [B,G,rep,Sq,Sk]."""
    B, Sq, Hq, D = q.shape
    G = k.shape[2]
    rep = Hq // G
    qg = q.reshape(B, Sq, G, rep, D)
    s = jnp.einsum("bsgrd,btgd->bgrst", qg, k,
                   preferred_element_type=jnp.float32)
    return s / jnp.sqrt(D).astype(jnp.float32)


def _online_update(s: jax.Array, v: jax.Array, mask: jax.Array,
                   m: jax.Array, l: jax.Array, acc: jax.Array):
    """One flash-attention accumulation step.

    s: [B,G,rep,Sq,Sk] raw scores; v: [B,Sk,G,D]; mask broadcastable to s
    (True = attend); m,l: [B,G,rep,Sq]; acc: [B,G,rep,Sq,D] (all f32)."""
    s = jnp.where(mask, s, NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])                       # [B,G,rep,Sq,Sk]
    l = l * alpha + p.sum(axis=-1)
    acc = acc * alpha[..., None] + jnp.einsum(
        "bgrst,btgd->bgrsd", p, v.astype(jnp.float32))
    return m_new, l, acc


def _ring_attend(q: jax.Array, k: jax.Array, v: jax.Array,
                 axis_name: str, sp: int) -> jax.Array:
    """Causal ring attention for one layer, inside shard_map.

    q/k/v: this device's sequence chunk [B,Sl,H*,D] (global positions
    ``my*Sl + i``). k/v make ``sp`` hops around the ring; each step masks
    by global causal order. Python loop — ``sp`` is static and small, and
    unrolling lets XLA overlap each hop's ppermute with the previous
    chunk's matmuls. Returns [B,Sl,Hq,D] in q.dtype."""
    B, Sl, Hq, D = q.shape
    G = k.shape[2]
    rep = Hq // G
    my = jax.lax.axis_index(axis_name)
    q_pos = my * Sl + jnp.arange(Sl)                        # [Sl] global

    m = jnp.full((B, G, rep, Sl), NEG_INF, jnp.float32)
    l = jnp.zeros((B, G, rep, Sl), jnp.float32)
    acc = jnp.zeros((B, G, rep, Sl, D), jnp.float32)
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    for t in range(sp):
        src = (my - t) % sp                 # ring position of this kv chunk
        k_pos = src * Sl + jnp.arange(Sl)                   # [Sl] global
        s = _chunk_scores(q, k)
        mask = (k_pos[None, :] <= q_pos[:, None])[None, None, None]
        m, l, acc = _online_update(s, v, mask, m, l, acc)
        if t != sp - 1:
            k, v = jax.lax.ppermute((k, v), axis_name, perm)

    out = acc / l[..., None]                                # causal: l >= 1
    # [B,G,rep,Sl,D] -> [B,Sl,Hq,D]
    return out.transpose(0, 3, 1, 2, 4).reshape(B, Sl, Hq, D).astype(q.dtype)


def _axes_for(config: ModelConfig):
    from ..models import family_for
    return family_for(config).param_axes(config)


def ring_prefill(params: dict, config: ModelConfig, tokens: jax.Array,
                 prompt_lens: jax.Array, mesh: Mesh,
                 mlp_fn=None) -> tuple[jax.Array, KVCache]:
    """Sequence-parallel prefill: the whole layer stack with the prompt
    sharded over ``sp`` and ring attention in place of dense attention.

    tokens: [B,S] right-padded, S divisible by sp; prompt_lens: [B].
    Returns (logits [B,S,vocab] f32 — sequence-sharded over sp — and a
    KVCache whose k/v [L,B,S,Hkv,D] are sharded on the sequence dim, ready
    for :func:`sp_decode_step`; its max_seq IS S, so budget S for prompt +
    generation). Numerics match models/llama.prefill (same f32 softmax).

    Cited contract: models/llama.py prefill — causal masking makes pad
    slots invisible to real queries; lengths gate decode.
    """
    sp = mesh.shape["sp"]
    tp = mesh.shape.get("tp", 1)
    ep = mesh.shape.get("ep", 1)
    assert mesh.size == sp * tp * ep, (
        f"ring path runs over sp (x tp | x ep) only "
        f"(mesh {dict(mesh.shape)}); set other axes to 1")
    assert tp == 1 or mlp_fn is None, \
        "MoE composes with the ring via ep (moe_ring_mlp_fn), not tp"
    assert ep == 1 or mlp_fn is not None, \
        "an ep axis shards experts; pass moe_ring_mlp_fn(config, 'ep')"
    assert config.num_kv_heads % tp == 0, (config.num_kv_heads, tp)
    B, S = tokens.shape
    assert S % sp == 0, f"seq {S} not divisible by sp={sp}"
    Sl = S // sp
    inv_freq = rope_frequencies(config)
    tp_axis = "tp" if tp > 1 else None

    def device_fn(params, tokens):
        # tokens: local chunk [B, Sl]; params: local tp head shards.
        my = jax.lax.axis_index("sp")
        positions = (my * Sl + jnp.arange(Sl))[None, :].astype(jnp.int32)
        positions = jnp.broadcast_to(positions, (B, Sl))
        h = params["embed"][tokens]

        def body(carry, xs):
            h, ck, cv = carry
            lp, layer = xs
            q, k, v = _attn_qkv_local(h, lp, config, inv_freq, positions)
            ck = jax.lax.dynamic_update_index_in_dim(ck, k, layer, 0)
            cv = jax.lax.dynamic_update_index_in_dim(cv, v, layer, 0)
            attn = _ring_attend(q, k, v, "sp", sp)
            h = _post_attn_tp(h, attn, lp, config, mlp_fn, tp_axis)
            return (h, ck, cv), None

        L = config.num_layers
        ck = jnp.zeros((L, B, Sl, config.num_kv_heads // tp,
                        config.head_dim), h.dtype)
        (h, ck, cv), _ = jax.lax.scan(
            body, (h, ck, jnp.zeros_like(ck)),
            (params["layers"], jnp.arange(L)))
        h = rms_norm(h, params["final_norm"], config.rms_norm_eps)
        lm_head = (params["embed"].T if config.tie_embeddings
                   else params["lm_head"])
        logits = mm(h, lm_head).astype(jnp.float32)
        return logits, ck, cv

    mapped = shard_map(
        device_fn, mesh=mesh,
        in_specs=(ring_param_specs(_axes_for(config)),
                  P(None, "sp")),
        out_specs=(P(None, "sp", None),
                   P(None, None, "sp", "tp" if tp > 1 else None, None),
                   P(None, None, "sp", "tp" if tp > 1 else None, None)),
        check_rep=False,
    )
    logits, ck, cv = mapped(params, tokens)
    return logits, KVCache(k=ck, v=cv,
                           lengths=prompt_lens.astype(jnp.int32))


def sp_decode_step(params: dict, config: ModelConfig, tokens: jax.Array,
                   cache: KVCache, mesh: Mesh,
                   active: Optional[jax.Array] = None,
                   mlp_fn=None) -> tuple[jax.Array, KVCache]:
    """One decode step against a sequence-sharded KV cache.

    Same contract as models/llama.decode_step (including the parked-row
    ``active`` semantics): each row writes cache slot ``lengths[b]`` —
    which lives on exactly one ring device; the others' out-of-range
    scatter indices are dropped — and attends to slots [0, lengths[b]]
    via per-device flash partials merged with pmax/psum. tokens: [B,1].
    Returns (logits [B,1,vocab] — replicated — and the advanced cache).
    """
    sp = mesh.shape["sp"]
    tp = mesh.shape.get("tp", 1)
    ep = mesh.shape.get("ep", 1)
    assert mesh.size == sp * tp * ep, "sp (x tp | x ep); see ring_prefill"
    assert tp == 1 or mlp_fn is None, \
        "MoE composes with the ring via ep (moe_ring_mlp_fn), not tp"
    assert ep == 1 or mlp_fn is not None, \
        "an ep axis shards experts; pass moe_ring_mlp_fn(config, 'ep')"
    B = tokens.shape[0]
    Sl = cache.k.shape[2] // sp
    inv_freq = rope_frequencies(config)
    tp_axis = "tp" if tp > 1 else None

    def device_fn(params, tokens, ck_all, cv_all, lengths):
        my = jax.lax.axis_index("sp")
        positions = lengths[:, None]                        # [B,1] global
        h = params["embed"][tokens]
        G, D = config.num_kv_heads // tp, config.head_dim
        rep = config.num_heads // config.num_kv_heads
        local_pos = jnp.arange(Sl) + my * Sl                # [Sl] global
        b_idx = jnp.arange(B)

        def body(carry, xs):
            h, ck, cv = carry
            lp, layer = xs
            q, k, v = _attn_qkv_local(h, lp, config, inv_freq, positions)
            # Scatter the new k/v at the owning device; everyone else's
            # local index is out of [0, Sl) and mode="drop" discards it.
            li = lengths - my * Sl                          # [B] local slot
            ck = ck.at[layer, b_idx, li].set(k[:, 0], mode="drop")
            cv = cv.at[layer, b_idx, li].set(v[:, 0], mode="drop")
            k_loc = jax.lax.dynamic_index_in_dim(ck, layer, 0, keepdims=False)
            v_loc = jax.lax.dynamic_index_in_dim(cv, layer, 0, keepdims=False)

            s = _chunk_scores(q, k_loc)                     # [B,G,rep,1,Sl]
            valid = (local_pos[None, :] < (lengths + 1)[:, None])  # [B,Sl]
            mask = valid[:, None, None, None, :]
            s = jnp.where(mask, s, NEG_INF)
            m_loc = s.max(axis=-1)                          # [B,G,rep,1]
            p = jnp.exp(s - m_loc[..., None])
            # Fully-masked shards contribute exp(NEG_INF - m_g) ~ 0.
            l_loc = jnp.where(m_loc > NEG_INF / 2,
                              p.sum(axis=-1), 0.0)
            acc_loc = jnp.einsum("bgrst,btgd->bgrsd", p,
                                 v_loc.astype(jnp.float32))
            m_g = jax.lax.pmax(m_loc, "sp")
            scale = jnp.where(m_loc > NEG_INF / 2,
                              jnp.exp(m_loc - m_g), 0.0)
            l_g = jax.lax.psum(l_loc * scale, "sp")
            acc_g = jax.lax.psum(acc_loc * scale[..., None], "sp")
            out = acc_g / l_g[..., None]                    # [B,G,rep,1,D]
            attn = out.transpose(0, 3, 1, 2, 4).reshape(
                B, 1, G * rep, D).astype(h.dtype)
            h = _post_attn_tp(h, attn, lp, config, mlp_fn, tp_axis)
            return (h, ck, cv), None

        (h, ck_all, cv_all), _ = jax.lax.scan(
            body, (h, ck_all, cv_all),
            (params["layers"], jnp.arange(config.num_layers)))
        h = rms_norm(h, params["final_norm"], config.rms_norm_eps)
        lm_head = (params["embed"].T if config.tie_embeddings
                   else params["lm_head"])
        logits = mm(h, lm_head).astype(jnp.float32)
        return logits, ck_all, cv_all

    kv_spec = P(None, None, "sp", "tp" if tp > 1 else None, None)
    mapped = shard_map(
        device_fn, mesh=mesh,
        in_specs=(ring_param_specs(_axes_for(config)), P(), kv_spec,
                  kv_spec, P()),
        out_specs=(P(), kv_spec, kv_spec),
        check_rep=False,
    )
    logits, ck, cv = mapped(params, tokens, cache.k, cache.v, cache.lengths)
    inc = (jnp.ones_like(cache.lengths) if active is None
           else active.astype(jnp.int32))
    return logits, KVCache(k=ck, v=cv, lengths=cache.lengths + inc)
