"""Per-node inbox: mutex-guarded append-only message buffer.

Reference: ``Inbox`` (go/cmd/node/main.go:97-128). Semantics preserved
exactly, including the deliberate quirks documented in SURVEY.md §2:

- append-only: ``drain`` never truncates, so history persists for the life
  of the process and repeated polls with ``after=""`` return everything —
  this is what makes chat history survive UI reloads in the reference.
- ``drain(after)`` with a non-empty ``after``: linear scan for the matching
  message ID, return the suffix strictly after it; an unknown ID returns the
  EMPTY list (main.go:116-127: ``found`` never flips, ``out`` stays empty) —
  a client polling with a stale cursor gets nothing, not duplicate history.

Additive over the reference: messages carrying a sender-minted ``msg_id``
(proto.mint_msg_id) are deduplicated — the at-least-once redelivery wire
(node.py Outbox) may deliver the same send twice (e.g. the ack was lost),
and the second copy must be suppressed so the client sees exactly-once.
Messages without a ``msg_id`` (old peers) keep the reference append-always
behavior.
"""

from __future__ import annotations

import collections
import threading
from typing import Optional

from .proto import ChatMessage

# Dedup ids remembered past the message cap: a redelivered copy of a
# message the cap already dropped must still be suppressed (it WAS
# delivered once), so ids outlive the messages by this factor.
_DEDUP_PER_MSG = 8

# Standalone dedup-id bound for the uncapped (reference-parity) inbox:
# the message buffer being unbounded is a deliberate reference quirk,
# but the dedup set is pure additive bookkeeping — cap it so
# at-least-once accounting can never OOM a node on its own.
_DEDUP_MAX = 4096


class Inbox:
    def __init__(self, max_messages: Optional[int] = None) -> None:
        """``max_messages`` is an additive safety valve (None = unbounded,
        matching the reference); when set, the oldest messages are dropped
        once the cap is exceeded so a hostile peer can't OOM the node."""
        self._mu = threading.Lock()
        self._msgs: list[ChatMessage] = []        # guarded-by: _mu
        self._max = max_messages
        self._seen: set[str] = set()              # guarded-by: _mu
        self._seen_order: collections.deque[str] = collections.deque()  # guarded-by: _mu

    def push(self, msg: ChatMessage) -> bool:
        """Append ``msg``; returns False when a duplicate ``msg_id`` was
        suppressed (the caller still acks — the original delivery won)."""
        with self._mu:
            if msg.msg_id:
                if msg.msg_id in self._seen:
                    return False
                self._seen.add(msg.msg_id)
                self._seen_order.append(msg.msg_id)
                cap = (_DEDUP_PER_MSG * self._max
                       if self._max is not None else _DEDUP_MAX)
                if len(self._seen_order) > cap:
                    self._seen.discard(self._seen_order.popleft())
            self._msgs.append(msg)
            if self._max is not None and len(self._msgs) > self._max:
                del self._msgs[: len(self._msgs) - self._max]
            return True

    def drain(self, after: str = "") -> list[ChatMessage]:
        with self._mu:
            if after == "":
                return list(self._msgs)
            for i, m in enumerate(self._msgs):
                if m.id == after:
                    return list(self._msgs[i + 1:])
            return []

    def __len__(self) -> int:
        with self._mu:
            return len(self._msgs)
