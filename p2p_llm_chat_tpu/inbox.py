"""Per-node inbox: mutex-guarded append-only message buffer.

Reference: ``Inbox`` (go/cmd/node/main.go:97-128). Semantics preserved
exactly, including the deliberate quirks documented in SURVEY.md §2:

- append-only: ``drain`` never truncates, so history persists for the life
  of the process and repeated polls with ``after=""`` return everything —
  this is what makes chat history survive UI reloads in the reference.
- ``drain(after)`` with a non-empty ``after``: linear scan for the matching
  message ID, return the suffix strictly after it; an unknown ID returns the
  EMPTY list (main.go:116-127: ``found`` never flips, ``out`` stays empty) —
  a client polling with a stale cursor gets nothing, not duplicate history.
"""

from __future__ import annotations

import threading
from typing import Optional

from .proto import ChatMessage


class Inbox:
    def __init__(self, max_messages: Optional[int] = None) -> None:
        """``max_messages`` is an additive safety valve (None = unbounded,
        matching the reference); when set, the oldest messages are dropped
        once the cap is exceeded so a hostile peer can't OOM the node."""
        self._mu = threading.Lock()
        self._msgs: list[ChatMessage] = []
        self._max = max_messages

    def push(self, msg: ChatMessage) -> None:
        with self._mu:
            self._msgs.append(msg)
            if self._max is not None and len(self._msgs) > self._max:
                del self._msgs[: len(self._msgs) - self._max]

    def drain(self, after: str = "") -> list[ChatMessage]:
        with self._mu:
            if after == "":
                return list(self._msgs)
            for i, m in enumerate(self._msgs):
                if m.id == after:
                    return list(self._msgs[i + 1:])
            return []

    def __len__(self) -> int:
        with self._mu:
            return len(self._msgs)
